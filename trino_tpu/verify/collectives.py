"""Collective-uniformity pass: static SPMD divergence-freedom proofs.

An SPMD mesh program deadlocks the classic way: one worker enters a
collective (all_to_all / all_gather / psum) that the others never issue, or
issues it in a different order, and the whole mesh hangs with no error.
Nothing in the engine *could* diverge today by accident — collectives are
compiled into uniform SPMD programs from plan structure — but nothing
PROVED it either, and the speculative-join retry path is exactly where a
future patch would introduce a per-worker branch around a collective (the
retry decision must come from the already-reduced on-device overflow flag,
never from one worker's local view).

This pass makes the property checkable:

  * `fragment_collectives(fragment)` statically enumerates, in execution
    order, every collective a distributed fragment's compiled step will
    issue — mirroring the mesh executor's dispatch (build side before
    dynamic filters before probe; slot-cap sizing before the fused
    exchange).  Each entry carries a `guard`:
      - `static`  — issued unconditionally from plan structure (uniform by
        construction: every worker runs the same program);
      - `reduced` — issued inside a loop/branch whose condition is a
        globally-reduced value identical on every worker (the speculative
        expansion's overflow flag: the host decision reads the all-worker
        [W] flag, so either every worker retries or none does);
      - anything else is a declared PER-WORKER condition and is rejected.
    A plan rewrite that makes a collective conditional must declare it by
    setting `collective_condition` on the node; `"reduced"` is the only
    sound value.  Undeclared conditionality cannot arise: the executor has
    no data-dependent dispatch besides the reduced retry loop.
  * `check_collective_uniformity(subplan)` walks every fragment and
    returns PlanViolations (`collective-divergence`,
    `collective-unsupported`) — wired into `verify_plan` strict mode next
    to `check_partitioning`, so every distributed TPC-H/TPC-DS plan is
    verified divergence-free at fragmentation time.
  * `collective_signature(subplan)` is the recorded per-fragment sequence
    of mesh collectives (kinds that move bytes over ICI).  The distributed
    runner stores it as `last_collective_signature`;
    `verify.device_residency` asserts a warm replay ISSUES the recorded
    sequence — the dynamic half of the proof, closing the loop between
    what the verifier enumerated and what the profile observed.

Entries marked `elidable` may legally be absent at runtime (runtime
exchange elision when the producing side is already placed; dynamic-filter
summaries skipped for dictionary-coded keys): elision decisions are made
once on the coordinator host from plan+layout state, so they are uniform
across workers by construction — they affect the signature match, never
uniformity.
"""

from __future__ import annotations

from dataclasses import dataclass

from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import (
    FIXED_ARBITRARY,
    FIXED_HASH,
    SOURCE,
    RemoteSourceNode,
    SubPlan,
)
from trino_tpu.verify.plan_checker import PlanViolation

#: partitioning kinds whose fragments execute as SPMD mesh programs
_DIST_KINDS = (SOURCE, FIXED_HASH, FIXED_ARBITRARY)

#: collective kinds that move bytes across the mesh interconnect — the
#: signature compares these (query_stats.COLLECTIVE_KINDS); "gather"
#: entries are host pulls, enumerated for the uniformity reasoning only
MESH_KINDS = ("all_to_all", "all_gather", "reduce")

GUARD_STATIC = "static"
GUARD_REDUCED = "reduced"

#: float/varchar join keys never produce a dynamic-filter summary
#: (dictionary codes are producer-local; float ranges are skipped)
_NO_DYNFILTER_TYPES = ("double", "real", "varchar", "char", "unknown")


@dataclass(frozen=True)
class Collective:
    kind: str  # all_to_all | all_gather | reduce | gather
    purpose: str  # repartition | broadcast | dynamic_filter | capacity_sizing
    origin: str  # node type that issues it
    guard: str = GUARD_STATIC
    #: may legally be skipped at runtime (uniform elision decision)
    elidable: bool = False


def _guard_for(node: P.PlanNode, default: str = GUARD_STATIC) -> str:
    """A node's declared conditionality (`collective_condition`); None means
    unconditional.  Anything but 'reduced' is a per-worker condition the
    checker rejects."""
    cond = getattr(node, "collective_condition", None)
    if cond is None:
        return default
    return str(cond)


class _Enumerator:
    """Mirror of trino_tpu.parallel.runner._MeshExecutor dispatch, emitting
    Collective entries instead of launching programs."""

    def __init__(self):
        self.out: list = []
        self.violations: list = []

    def _emit(self, node, kind, purpose, guard=None, elidable=False):
        g = _guard_for(node) if guard is None else guard
        self.out.append(
            Collective(kind, purpose, type(node).__name__, g, elidable)
        )

    def walk(self, node: P.PlanNode) -> None:
        m = getattr(self, "_c_" + type(node).__name__, None)
        if m is not None:
            m(node)
            return
        # unknown node in a distributed fragment: structure-preserving
        # default (unary chains defer; no collective of their own)
        for c in node.children:
            self.walk(c)

    # -- sources ---------------------------------------------------------------

    def _c_RemoteSourceNode(self, node: RemoteSourceNode) -> None:
        if node.exchange_kind == "broadcast":
            self._emit(node, "all_gather", "broadcast")
        elif node.exchange_kind == "repartition":
            # runtime exchange elision may skip this when the child
            # fragment's output is already placed on the requested keys
            self._emit(node, "all_to_all", "repartition", elidable=True)
        else:
            self.violations.append(
                PlanViolation(
                    "collective-unsupported", node,
                    f"exchange kind {node.exchange_kind!r} cannot feed a "
                    "distributed fragment (the placer should have cut a "
                    "SINGLE fragment here)",
                )
            )

    def _c_TableScanNode(self, node) -> None:
        pass  # host-side feed; bucketize happens before the mesh

    # -- aggregation -----------------------------------------------------------

    def _c_AggregationNode(self, node: P.AggregationNode) -> None:
        if not isinstance(node.source, RemoteSourceNode):
            # exchange elided by the placer: colocated single-stage agg
            self.walk(node.source)
            return
        # fused exchange: slot-cap counts sync, then bucketize+all_to_all+
        # final/single-stage step as one program (same shape for the
        # partial/final and the distinct/holistic single-stage paths).
        # A group-count certificate (verify/capacity.py) licenses the slot
        # cap from the proven group bound — elidable for the same reason
        # as the licensed join's sizing gather: the runner's accept/
        # decline decision is host-side and uniform by construction
        self._emit(
            node, "gather", "capacity_sizing",
            elidable=getattr(node, "capacity_cert", None) is not None,
        )
        self._emit(node.source, "all_to_all", "repartition")

    # -- joins -----------------------------------------------------------------

    def _side(self, side_node) -> None:
        """A join input: a RemoteSource child fragment contributes nothing
        here (its body enumerates under its own fragment id); an inline
        subtree executes in THIS fragment."""
        if not isinstance(side_node, RemoteSourceNode):
            self.walk(side_node)

    def _dynfilter_emittable(self, criteria):
        """(emit, certain): does the inner join register a dynamic-filter
        summary?  Skipped per-criterion for dictionary-coded (varchar) and
        float keys; certain only when every key is integer-kind."""
        kinds = []
        for _, rsym in criteria:
            t = getattr(rsym, "type", None)
            name = getattr(t, "name", "unknown")
            kinds.append(name not in _NO_DYNFILTER_TYPES)
        return any(kinds), all(kinds)

    def _c_JoinNode(self, node: P.JoinNode) -> None:
        if not node.criteria:
            for c in node.children:
                self._side(c)
            return
        # execution order: build side first, then its dynamic-filter
        # summary, then the probe side, then placement, then expansion
        self._side(node.right)
        if node.kind == "inner":
            emit, certain = self._dynfilter_emittable(node.criteria)
            if emit:
                self._emit(
                    node, "reduce", "dynamic_filter", elidable=not certain
                )
        self._side(node.left)
        if node.distribution == "broadcast":
            self._emit(node, "all_gather", "broadcast")
        else:
            for side in (node.right, node.left):  # build placed first
                if (
                    isinstance(side, RemoteSourceNode)
                    and side.exchange_kind == "repartition"
                ):
                    self._emit(side, "all_to_all", "repartition")
        # speculative/sized expansion: the overflow-flag read, and the
        # retry decision it feeds, use the ALL-worker [W] flag — reduced,
        # therefore uniform (the pass's interesting customer).  A join
        # carrying a capacity certificate (verify/capacity.py) is PROOF-
        # GATED: the licensed path compiles at the certified capacity and
        # issues no sizing gather at all — elidable, because the runner
        # falls back to the sizing path when the seal doesn't match the
        # executing mesh (the decision is made once on the coordinator,
        # uniform by construction, like exchange elision)
        self._emit(
            node, "gather", "capacity_sizing",
            guard=_guard_for(node, GUARD_REDUCED),
            elidable=getattr(node, "capacity_cert", None) is not None,
        )

    def _c_SemiJoinNode(self, node: P.SemiJoinNode) -> None:
        self._side(node.source)
        if node.filter is not None:
            # residual semi join: repartition both sides on the key (either
            # may elide when already placed), then the sized expansion
            for side in (node.source, node.filtering):
                self._emit(side, "all_to_all", "repartition", elidable=True)
            self._emit(
                node, "gather", "capacity_sizing",
                guard=_guard_for(node, GUARD_REDUCED),
            )
            return
        self._emit(node, "all_gather", "broadcast")


def fragment_collectives(sub: SubPlan) -> tuple:
    """(collectives, violations) for ONE fragment's body (no recursion into
    child fragments)."""
    e = _Enumerator()
    if sub.fragment.partitioning.kind in _DIST_KINDS:
        e.walk(sub.fragment.root)
    else:
        # SINGLE/COORDINATOR_ONLY fragments run on the host over gathered
        # inputs: no mesh collectives of their own, and nothing to diverge
        pass
    return tuple(e.out), e.violations


def collective_signature(sub: SubPlan) -> dict:
    """{fragment id: ((kind, purpose, elidable), ...)} over mesh-collective
    kinds, in issue order — the statically recorded sequence
    `verify.device_residency` holds warm replays to."""
    out: dict = {}
    for s in _walk_subplans(sub):
        cols, _ = fragment_collectives(s)
        out[s.fragment.id] = tuple(
            (c.kind, c.purpose, c.elidable)
            for c in cols
            if c.kind in MESH_KINDS
        )
    return out


def _walk_subplans(sub: SubPlan):
    yield sub
    for c in sub.children:
        yield from _walk_subplans(c)


def check_collective_uniformity(sub: SubPlan) -> list:
    """Verify every fragment's collective sequence is divergence-free:
    well-defined from plan structure, identical across workers, and never
    conditional on per-worker data.  Returns PlanViolations (empty =
    proven uniform)."""
    violations: list = []

    def visit(s: SubPlan) -> None:
        cols, vs = fragment_collectives(s)
        violations.extend(vs)
        for c in cols:
            if c.guard not in (GUARD_STATIC, GUARD_REDUCED):
                violations.append(
                    PlanViolation(
                        "collective-divergence", s.fragment.root,
                        f"fragment {s.fragment.id}: {c.kind}/{c.purpose} "
                        f"from {c.origin} is conditional on per-worker "
                        f"data ({c.guard!r}) — a worker that skips it "
                        "deadlocks the mesh; gate it on a globally-"
                        "reduced value (collective_condition='reduced') "
                        "or issue it unconditionally",
                    )
                )
        for child in s.children:
            visit(child)

    visit(sub)
    return violations


# -- signature matching (the dynamic half, used by device_residency) -----------


def signature_problems(expected: dict, actual: dict) -> list:
    """Compare the static signature against an executed run's recorded
    per-fragment mesh-collective sequence ({fid: ((kind, purpose), ...)}).
    Expected entries marked elidable may be absent; everything else must
    appear, in order, with nothing unexpected.  Returns human-readable
    problem strings (empty = the replay issued the recorded sequence)."""
    def matches(exp, act, i=0, j=0) -> bool:
        # backtracking (not greedy first-fit): an ELIDED entry followed by a
        # required one with the same (kind, purpose) must not steal the
        # issued collective from the required slot.  Sequences are tiny
        # (a handful per fragment), so plain recursion is fine.
        if i == len(exp):
            return j == len(act)
        kind, purpose, elidable = exp[i]
        if (
            j < len(act)
            and act[j] == (kind, purpose)
            and matches(exp, act, i + 1, j + 1)
        ):
            return True
        return elidable and matches(exp, act, i + 1, j)

    problems = []
    for fid in sorted(set(expected) | set(actual)):
        exp = list(expected.get(fid, ()))
        act = list(actual.get(fid, ()))
        if not matches(exp, act):
            problems.append(
                f"fragment {fid}: issued collective sequence "
                f"{act} does not match the recorded signature "
                f"{[(k, p) + (('elidable',) if e else ()) for k, p, e in exp]}"
            )
    return problems


# -- CLI: verify every distributed TPC-H + TPC-DS fragment ---------------------


def verify_benchmarks(n_workers: int = 8, verbose: bool = False) -> int:
    """Plan every TPC-H and TPC-DS query distributed and run the
    uniformity pass in strict mode over every fragment.  Returns the
    number of fragments verified; raises PlanViolation on the first
    divergence.  (CI runs this via `python -m trino_tpu.verify.collectives`
    next to the lint gate.)"""
    from trino_tpu.parallel.runner import DistributedQueryRunner

    fragments = 0
    suites = (
        ("tpch", "tiny", "trino_tpu.connectors.tpch.queries"),
        ("tpcds", "tiny", "trino_tpu.connectors.tpcds.queries"),
    )
    for catalog, schema, mod in suites:
        import importlib

        queries = importlib.import_module(mod).QUERIES
        r = DistributedQueryRunner(
            catalog=catalog, schema=schema, n_workers=n_workers
        )
        r.properties.set("verify_plan", "strict")
        for q in sorted(queries):
            sub = r.create_subplan(r.create_plan(queries[q]))
            # create_subplans already enforced the pass (strict mode); run
            # it again explicitly so this gate stands alone
            violations = check_collective_uniformity(sub)
            if violations:
                raise violations[0]
            n = sum(1 for _ in sub.all_fragments())
            fragments += n
            if verbose:
                sig = collective_signature(sub)
                print(f"{catalog} {q}: {n} fragment(s), signature {sig}")
    return fragments


def main() -> int:  # pragma: no cover - CLI entry
    import argparse

    ap = argparse.ArgumentParser(
        description="verify collective uniformity over all TPC-H + TPC-DS "
        "distributed plans"
    )
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    n = verify_benchmarks(args.workers, args.verbose)
    print(f"collective-uniformity: {n} fragments verified divergence-free")
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
