"""Numeric-safety verifier: abstract interpretation over the expression IR.

The device compiles every scalar expression to fixed-width integer/float
kernels (expr/compiler.py + expr/functions.py): short decimals are scaled
int64, integers keep their declared width, long decimals are two int64 limb
planes.  None of those kernels trap — int overflow wraps two's-complement,
a mis-scaled decimal branch silently reinterprets units, a float detour
silently rounds an exact value, and a dropped validity plane resurrects
NULLs as zeros.  The reference engine throws at runtime; a vectorized XLA
program cannot, so the property must be PROVEN statically instead.

This pass propagates a lattice of (dtype, decimal precision/scale, value
interval, nullability) — `verify.ranges.Interval` in scaled units — through
every expression, mirroring the exact arithmetic the compiled kernels
perform (rescale-then-add, multiply-then-rescale, truncating division).
Facts come from literal values, declared type precisions, and connector
generator statistics (exact by construction); CBO estimates are never
admitted.  Each hazard becomes an `Issue` under one of the rules:

  rule                | flags
  --------------------+-----------------------------------------------------
  int-overflow        | integer arithmetic whose result interval exceeds the
                      | device dtype — silent two's-complement wrap
  decimal-overflow    | decimal arithmetic/rescale whose exact value can
                      | exceed its i64 (short) / i128 (limb) accumulator
  scale-mismatch      | branch-merge forms (IF/CASE/COALESCE/NULLIF) mixing
                      | decimal scales without a rescale — the compiler
                      | broadcasts raw scaled ints, so units silently differ
  float-contamination | an exact decimal value computed through a float
                      | representation (float argument to a decimal-typed
                      | op, or a float->decimal CAST) — exactness silently
                      | lost to f64 rounding
  dropped-validity    | a construct that collapses or discards a finer
                      | validity plane consuming a nullable argument (the
                      | rectangular ARRAY constructor's documented
                      | per-element collapse; extensible table)

Findings triage through the `numeric_safety` baseline map in
tools/lint_baseline.json — keyed by a stable (rule, operator-signature)
string, one reviewed justification per entry, same workflow as the
concurrency pass's `unguarded_state`.  The CI sweep
(`python -m trino_tpu.verify.numeric`) walks every expression of every
TPC-H + TPC-DS plan and reports each as PROVEN-SAFE / BASELINED /
VIOLATION; any unbaselined VIOLATION fails.

The same interval machinery has a second job: **licensing**.
`sum_certificate()` turns an analyzed aggregation input into a
`verify.ranges.RangeCertificate` — per-row magnitude bound x total-row
bound — that the planner attaches to sum/avg specs; when the certificate
proves every partial sum fits int64, the aggregation and window kernels
compile single-plane i64 segment sums with NO runtime fits check and NO
limb-plane traffic (the generalization of `_sum128`'s static precision
proof; see ops/aggregation.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from trino_tpu import types as T
from trino_tpu.expr.ir import (
    Call,
    Expr,
    Form,
    InputRef,
    Lambda,
    LambdaParam,
    Literal,
    SpecialForm,
    SymbolRef,
)
from trino_tpu.verify import ranges as R
from trino_tpu.verify.capacity import FLIPPED_CMP, conjuncts
from trino_tpu.verify.ranges import Interval, RangeCertificate

RULES = (
    "int-overflow",
    "decimal-overflow",
    "scale-mismatch",
    "float-contamination",
    "dropped-validity",
)

#: forms that merge branch values by raw broadcast (expr/compiler.py
#: _case_fold/_form_coalesce/_form_nullif): a decimal branch whose scale
#: differs from the output scale is silently reinterpreted
_BRANCH_FORMS = (Form.IF, Form.CASE, Form.COALESCE, Form.NULLIF)

#: constructs that collapse a finer validity plane (rule dropped-validity):
#: the rectangular ARRAY layout tracks validity per ROW, so a nullable
#: element's per-element NULL is unrepresentable and nulls the whole array
#: (documented deviation in expr/compiler.py _form_array)
_VALIDITY_COLLAPSING_FORMS = (Form.ARRAY,)

#: known value bounds of scalar functions the interval domain would
#: otherwise widen to the full result dtype (year(x) * 10000 must not read
#: as a bigint-range product); bounds are intentionally generous — they
#: only need to be TRUE, not tight
_FN_BOUNDS = {
    "year": Interval(-30000, 30000),
    "quarter": Interval(1, 4),
    "month": Interval(1, 12),
    "week": Interval(1, 53),
    "day": Interval(1, 31),
    "day_of_month": Interval(1, 31),
    "day_of_week": Interval(1, 7),
    "day_of_year": Interval(1, 366),
    "hour": Interval(0, 23),
    "minute": Interval(0, 59),
    "second": Interval(0, 59),
    "length": Interval(0, 1 << 31),
    "cardinality": Interval(0, 1 << 31),
    "sign": Interval(-1, 1),
}


@dataclass(frozen=True)
class Fact:
    """Abstract value: declared type + scaled-unit interval + nullability.

    tracked: the interval derives entirely from admissible bound sources
    (literals, declared decimal/integer precision of stored columns,
    generator statistics).  Untracked facts keep honest (type-wide)
    intervals but do not RAISE overflow findings — an unknown function's
    full-dtype result interval is not evidence of a wrap hazard — and never
    license a fast-path certificate."""

    type: T.Type
    interval: Interval
    nullable: bool = True
    tracked: bool = True

    @staticmethod
    def untracked(t: T.Type, nullable: bool = True) -> "Fact":
        return Fact(t, R.type_interval(t), nullable, tracked=False)


@dataclass(frozen=True)
class Issue:
    rule: str
    signature: str  # stable baseline key payload (operator + operand types)
    message: str

    def key(self) -> str:
        return f"{self.rule}:{self.signature}"

    def __str__(self) -> str:
        return f"[{self.rule}] {self.signature}: {self.message}"


class Env:
    """Bound facts for the free references of an expression: symbol names
    (logical plans) and/or input channels (locally planned exprs)."""

    def __init__(self, symbols: dict = None, channels: dict = None):
        self.symbols = dict(symbols or {})
        self.channels = dict(channels or {})

    def sym(self, name: str) -> Optional[Fact]:
        return self.symbols.get(name)

    def ref(self, channel: int) -> Optional[Fact]:
        return self.channels.get(channel)

    @staticmethod
    def for_layout(symbols, sym_env: "Env") -> "Env":
        """Channel-keyed env for a physical layout (symbols[i] -> channel i)."""
        ch = {}
        for i, s in enumerate(symbols):
            f = sym_env.sym(s.name)
            if f is not None:
                ch[i] = f
        return Env(sym_env.symbols, ch)


class Analyzer:
    """One pass over one expression; collects Issues, returns Facts."""

    def __init__(self, env: Env = None):
        self.env = env or Env()
        self.issues: list = []
        self._memo: dict = {}

    # -- helpers --------------------------------------------------------------

    def _issue(self, rule: str, signature: str, message: str) -> None:
        self.issues.append(Issue(rule, signature, message))

    @staticmethod
    def _sig(op: str, *types: T.Type, out: T.Type = None) -> str:
        s = f"{op}({', '.join(t.name for t in types)})"
        if out is not None:
            s += f"->{out.name}"
        return s

    def _check_fits(
        self, rule: str, sig: str, iv: Interval, room: Interval,
        tracked: bool, what: str,
    ) -> None:
        """Flag when a TRACKED interval can escape its accumulator."""
        if tracked and not iv.within(room):
            self._issue(rule, sig, f"{what}: value interval {iv} can exceed "
                                   f"the device accumulator {room}")

    # -- entry ----------------------------------------------------------------

    def analyze(self, expr: Expr) -> Fact:
        hit = self._memo.get(id(expr))
        # id() memo is safe here: the analyzer lives for one pass and keeps
        # every visited Expr alive through the memo itself
        if hit is not None:
            return hit
        fact = self._analyze(expr)
        self._memo[id(expr)] = fact
        return fact

    def _analyze(self, e: Expr) -> Fact:
        if isinstance(e, Literal):
            return self._literal(e)
        if isinstance(e, InputRef):
            f = self.env.ref(e.channel)
            return f if f is not None else self._column_fact(e.type)
        if isinstance(e, SymbolRef):
            f = self.env.sym(e.name)
            return f if f is not None else self._column_fact(e.type)
        if isinstance(e, LambdaParam):
            return Fact.untracked(e.type)
        if isinstance(e, Lambda):
            return self.analyze(e.body)
        if isinstance(e, Call):
            return self._call(e)
        if isinstance(e, SpecialForm):
            return self._form(e)
        return Fact.untracked(getattr(e, "type", T.UNKNOWN))

    def _column_fact(self, t: T.Type) -> Fact:
        """A stored column with no statistics: its DECLARED precision is
        still a real bound for exact types (a decimal(12,2) column holds
        |v| < 10**12 by the type contract), so the fact stays tracked."""
        if R.is_exact_type(t) and not isinstance(
            t, (T.ArrayType, T.MapType, T.RowType)
        ):
            return Fact(t, R.type_interval(t), nullable=True, tracked=True)
        return Fact.untracked(t)

    def _literal(self, lit: Literal) -> Fact:
        t = lit.type
        if lit.value is None:
            return Fact(t, Interval.point(0), nullable=True)
        if isinstance(t, T.DecimalType):
            from decimal import Decimal

            scaled = int(
                (Decimal(str(lit.value)) * t.scale_factor).to_integral_value()
            )
            return Fact(t, Interval.point(scaled), nullable=False)
        if isinstance(lit.value, bool):
            return Fact(t, Interval.point(int(lit.value)), nullable=False)
        if isinstance(lit.value, int) and R.is_exact_type(t):
            return Fact(t, Interval.point(lit.value), nullable=False)
        return Fact(t, R.type_interval(t), nullable=False,
                    tracked=R.is_exact_type(t))

    # -- calls ----------------------------------------------------------------

    def _call(self, call: Call) -> Fact:
        args = [self.analyze(a) for a in call.args]
        nullable = any(a.nullable for a in args)  # null-in/null-out default
        name = call.name
        rt = call.type
        if name in ("$add", "$sub"):
            return self._add_sub(call, args, nullable)
        if name == "$mul":
            return self._mul(call, args, nullable)
        if name == "$div":
            return self._div(call, args, nullable)
        if name == "$neg":
            a = args[0]
            iv = a.interval.neg()
            sig = self._sig(name, a.type, out=rt)
            self._check_fits(
                self._overflow_rule(rt), sig, iv, R.dtype_interval(rt),
                a.tracked, "negation",
            )
            return Fact(rt, iv, nullable, a.tracked)
        if name in ("$eq", "$ne", "$lt", "$le", "$gt", "$ge"):
            # comparisons rescale via _align_numeric: the REScale can wrap
            # short decimals before comparing
            self._check_align(name, args)
            return Fact(T.BOOLEAN, Interval(0, 1), nullable)
        if name == "abs":
            a = args[0]
            m = a.interval.max_abs()
            iv = Interval(0, m) if m is not None else R.type_interval(rt)
            return Fact(rt, iv, nullable, a.tracked)
        self._check_float_contamination(self._sig(name, *[a.type for a in args], out=rt), rt, args)
        b = _FN_BOUNDS.get(name)
        if b is not None:
            return Fact(rt, b, nullable, tracked=True)
        if name in ("$mod",):
            m = args[1].interval.max_abs()
            if m is not None:
                return Fact(rt, Interval(-m, m), nullable, args[1].tracked)
        # unknown scalar function: honest type-wide interval, untracked
        return Fact.untracked(rt, nullable)

    def _overflow_rule(self, t: T.Type) -> str:
        return "decimal-overflow" if isinstance(t, T.DecimalType) else "int-overflow"

    def _check_float_contamination(self, sig: str, rt: T.Type, args) -> None:
        if isinstance(rt, T.DecimalType) and any(
            a.type.name in ("real", "double") for a in args
        ):
            self._issue(
                "float-contamination", sig,
                "exact decimal result computed from a float argument — the "
                "value detours through f64 and silently loses exactness",
            )

    def _check_align(self, op: str, args) -> None:
        """_align_numeric rescales short decimals to the max operand scale
        in i64: the rescaled operand can wrap before the op even runs."""
        da = [a for a in args if isinstance(a.type, T.DecimalType)]
        if len(da) < 2 or any(a.type.is_long for a in da):
            return
        s = max(a.type.scale for a in da)
        for a in da:
            iv = a.interval.scale_pow10(s - a.type.scale)
            self._check_fits(
                "decimal-overflow",
                self._sig(op, *[x.type for x in args]),
                iv, R.I64_INTERVAL, a.tracked,
                f"operand rescale to scale {s}",
            )

    def _add_sub(self, call: Call, args, nullable: bool) -> Fact:
        a, b = args
        rt = call.type
        tracked = a.tracked and b.tracked
        sig = self._sig(call.name, a.type, b.type, out=rt)
        self._check_float_contamination(sig, rt, args)
        if not R.is_exact_type(rt):
            return Fact.untracked(rt, nullable)
        da = isinstance(a.type, T.DecimalType)
        db = isinstance(b.type, T.DecimalType)
        if da or db:
            long_path = (
                (da and a.type.is_long) or (db and b.type.is_long)
                or (isinstance(rt, T.DecimalType) and rt.is_long)
            )
            out_scale = rt.scale if isinstance(rt, T.DecimalType) else 0
            sa = a.type.scale if da else 0
            sb = b.type.scale if db else 0
            if long_path:
                # exact two-limb add at the OUTPUT scale (functions._arith)
                ia = a.interval.scale_pow10(out_scale - sa)
                ib = b.interval.scale_pow10(out_scale - sb)
                iv = ia.add(ib) if call.name == "$add" else ia.sub(ib)
                room = R.dtype_interval(rt)
                self._check_fits(
                    "decimal-overflow", sig, iv, room, tracked, "limb add"
                )
                return Fact(rt, iv, nullable, tracked)
            # short path: rescale both to max scale in i64, add, rescale out
            s = max(sa, sb)
            ia = a.interval.scale_pow10(s - sa)
            ib = b.interval.scale_pow10(s - sb)
            for side, iv_side in (("left", ia), ("right", ib)):
                self._check_fits(
                    "decimal-overflow", sig, iv_side, R.I64_INTERVAL,
                    tracked, f"{side} operand rescale to scale {s}",
                )
            iv = ia.add(ib) if call.name == "$add" else ia.sub(ib)
            self._check_fits(
                "decimal-overflow", sig, iv, R.I64_INTERVAL, tracked,
                "short-decimal accumulate",
            )
            iv = iv.scale_pow10(out_scale - s)
            return Fact(rt, iv, nullable, tracked)
        # integer kinds: the kernel computes in the promoted operand dtype,
        # which equals the result dtype for the planner's typed IR
        iv = a.interval.add(b.interval) if call.name == "$add" else a.interval.sub(b.interval)
        self._check_fits(
            "int-overflow", sig, iv, R.dtype_interval(rt), tracked,
            "integer add/sub",
        )
        return Fact(rt, iv, nullable, tracked)

    def _mul(self, call: Call, args, nullable: bool) -> Fact:
        a, b = args
        rt = call.type
        tracked = a.tracked and b.tracked
        sig = self._sig("$mul", a.type, b.type, out=rt)
        self._check_float_contamination(sig, rt, args)
        if not R.is_exact_type(rt):
            return Fact.untracked(rt, nullable)
        da = isinstance(a.type, T.DecimalType)
        db = isinstance(b.type, T.DecimalType)
        if da or db:
            sa = a.type.scale if da else 0
            sb = b.type.scale if db else 0
            out_scale = rt.scale if isinstance(rt, T.DecimalType) else sa + sb
            iv = a.interval.mul(b.interval)  # product at scale sa+sb
            long_path = (
                (da and a.type.is_long) or (db and b.type.is_long)
                or (isinstance(rt, T.DecimalType) and rt.is_long)
            )
            if long_path:
                # mul64x64 / mul128_by_i64vec are exact to 128 bits; the
                # post-rescale must still fit the planes
                iv = iv.scale_pow10(out_scale - (sa + sb))
                self._check_fits(
                    "decimal-overflow", sig, iv, R.I128_INTERVAL, tracked,
                    "limb product",
                )
                if isinstance(rt, T.DecimalType) and not rt.is_long:
                    self._check_fits(
                        "decimal-overflow", sig, iv, R.I64_INTERVAL, tracked,
                        "limb product narrowed to a short result",
                    )
                return Fact(rt, iv, nullable, tracked)
            # short x short with a short result: raw i64 product, then
            # rescale — BOTH can wrap
            self._check_fits(
                "decimal-overflow", sig, iv, R.I64_INTERVAL, tracked,
                "short-decimal product (computed in i64 before rescale)",
            )
            iv = iv.scale_pow10(out_scale - (sa + sb))
            self._check_fits(
                "decimal-overflow", sig, iv, R.I64_INTERVAL, tracked,
                "product rescale",
            )
            return Fact(rt, iv, nullable, tracked)
        iv = a.interval.mul(b.interval)
        self._check_fits(
            "int-overflow", sig, iv, R.dtype_interval(rt), tracked,
            "integer product",
        )
        return Fact(rt, iv, nullable, tracked)

    def _div(self, call: Call, args, nullable: bool) -> Fact:
        a, b = args
        rt = call.type
        tracked = a.tracked and b.tracked
        sig = self._sig("$div", a.type, b.type, out=rt)
        self._check_float_contamination(sig, rt, args)
        if not R.is_exact_type(rt):
            return Fact.untracked(rt, nullable)
        # div-by-zero nulls (TRY semantics): result is nullable regardless
        nullable = True
        if isinstance(rt, T.DecimalType) and not rt.is_long:
            sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
            sb = b.type.scale if isinstance(b.type, T.DecimalType) else 0
            shift = rt.scale - sa + sb
            num = a.interval.scale_pow10(shift) if shift > 0 else a.interval
            self._check_fits(
                "decimal-overflow", sig, num, R.I64_INTERVAL, tracked,
                f"numerator rescale by 10**{max(shift, 0)}",
            )
            iv = num.truncdiv(b.interval)
            # +1 unit covers the round-half-away bump
            iv = iv.add(Interval(-1, 1))
            return Fact(rt, iv, nullable, tracked)
        iv = a.interval.truncdiv(b.interval)
        return Fact(rt, iv, nullable, tracked)

    # -- special forms ---------------------------------------------------------

    def _form(self, f: SpecialForm) -> Fact:
        args = [self.analyze(a) for a in f.args]
        rt = f.type
        form = f.form
        if form in (Form.AND, Form.OR, Form.NOT, Form.IS_NULL, Form.IN,
                    Form.BETWEEN):
            if form in (Form.IN, Form.BETWEEN):
                self._check_align(form.value, args)
            nullable = form != Form.IS_NULL and any(a.nullable for a in args)
            return Fact(T.BOOLEAN, Interval(0, 1), nullable)
        if form == Form.CAST:
            return self._cast(f, args[0])
        if form == Form.TRY:
            a = args[0]
            return Fact(a.type, a.interval, True, a.tracked)
        if form in _BRANCH_FORMS:
            return self._branches(f, args)
        if form in _VALIDITY_COLLAPSING_FORMS:
            elems = [a for a in args if a.nullable]
            if elems:
                self._issue(
                    "dropped-validity",
                    self._sig(form.value, *[a.type for a in args], out=rt),
                    "the rectangular array layout tracks validity per ROW: "
                    "a nullable element's per-element NULL collapses into "
                    "nulling the whole value — wrap elements in COALESCE or "
                    "prove them non-null",
                )
            return Fact.untracked(rt)
        if form == Form.SUBSCRIPT:
            base = args[0]
            et = rt
            iv = R.type_interval(et)
            return Fact(et, iv, True, tracked=False)
        # ROW / DEREFERENCE / unmodeled forms
        return Fact.untracked(rt, any(a.nullable for a in args))

    def _cast(self, f: SpecialForm, a: Fact) -> Fact:
        rt = f.type
        sig = self._sig("cast", a.type, out=rt)
        nullable = a.nullable
        if isinstance(rt, T.DecimalType) and a.type.name in ("real", "double"):
            self._issue(
                "float-contamination", sig,
                "float -> decimal cast: the exact-decimal path downstream "
                "inherits f64 rounding error",
            )
            return Fact(rt, R.type_interval(rt), nullable, tracked=False)
        if isinstance(rt, T.DecimalType) and R.is_exact_type(a.type):
            sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
            iv = a.interval.scale_pow10(rt.scale - sa)
            room = R.dtype_interval(rt)
            self._check_fits(
                "decimal-overflow", sig, iv, room, a.tracked,
                "decimal rescale on cast",
            )
            return Fact(rt, iv, nullable, a.tracked)
        if T.is_integer_kind(rt) and R.is_exact_type(a.type):
            sa = a.type.scale if isinstance(a.type, T.DecimalType) else 0
            iv = a.interval.scale_pow10(-sa)
            # compile_cast nulls out-of-range values (no silent wrap), so
            # the fact narrows to the dtype range and turns nullable when
            # clipping is possible
            room = R.dtype_interval(rt)
            if not iv.within(room):
                nullable = True
            iv = Interval(
                room.lo if iv.lo is None else max(iv.lo, room.lo),
                room.hi if iv.hi is None else min(iv.hi, room.hi),
            )
            return Fact(rt, iv, nullable, a.tracked)
        if R.is_exact_type(rt):
            return Fact(rt, R.type_interval(rt), nullable, tracked=False)
        return Fact.untracked(rt, nullable)

    def _branches(self, f: SpecialForm, args) -> Fact:
        rt = f.type
        form = f.form
        # branch VALUE positions per compiler._case_fold/_form_coalesce —
        # keep the index shapes aligned with _branch_exprs below, whose zip
        # pairs facts with their Expr nodes
        implicit_null = False
        if form == Form.IF:
            vals = args[1:]
            implicit_null = len(args) < 3
        elif form == Form.CASE:
            if len(args) % 2 == 1:
                vals = [args[i] for i in range(1, len(args) - 1, 2)]
                vals.append(args[-1])
            else:
                # pairs only: the compiler supplies an implicit NULL
                # default, so the result is nullable whenever some row
                # matches no branch
                vals = [args[i] for i in range(1, len(args), 2)]
                implicit_null = True
        elif form == Form.NULLIF:
            vals = args[:1]
        else:  # COALESCE
            vals = args
        if isinstance(rt, T.DecimalType) and not rt.is_long:
            for v, e in zip(vals, _branch_exprs(f)):
                if (
                    isinstance(v.type, T.DecimalType)
                    and v.type.scale != rt.scale
                    and not (isinstance(e, Literal) and e.value is None)
                ):
                    self._issue(
                        "scale-mismatch",
                        self._sig(form.value, v.type, out=rt),
                        f"branch value at scale {v.type.scale} merged into "
                        f"a scale-{rt.scale} result by raw broadcast — the "
                        "compiler does not rescale branch data; insert an "
                        "explicit CAST",
                    )
        iv = None
        tracked = True
        for v in vals:
            vi = v.interval
            if isinstance(v.type, T.DecimalType) and isinstance(rt, T.DecimalType):
                vi = vi.scale_pow10(rt.scale - v.type.scale)
            iv = vi if iv is None else iv.union(vi)
            tracked = tracked and v.tracked
        nullable = (
            any(a.nullable for a in args)
            or form in (Form.NULLIF,)
            or implicit_null
        )
        if form == Form.COALESCE and vals and not vals[-1].nullable:
            nullable = False
        return Fact(
            rt, iv if iv is not None else R.type_interval(rt), nullable,
            tracked and R.is_exact_type(rt),
        )


def _branch_exprs(f: SpecialForm):
    """The Expr nodes in branch-VALUE positions, aligned with _branches."""
    args = list(f.args)
    if f.form == Form.IF:
        return args[1:]
    if f.form == Form.CASE:
        if len(args) % 2 == 1:
            return [args[i] for i in range(1, len(args) - 1, 2)] + [args[-1]]
        return [args[i] for i in range(1, len(args), 2)]
    if f.form == Form.NULLIF:
        return args[:1]
    return args


def analyze_expr(expr: Expr, env: Env = None):
    """-> (Fact, [Issue]) for one expression."""
    a = Analyzer(env)
    fact = a.analyze(expr)
    return fact, a.issues


# -- plan-level bound propagation ----------------------------------------------


#: connector catalogs whose table_statistics are EXACT generator parameters
#: (admissible as proof sources); anything else contributes only declared
#: type precisions
_EXACT_STATS_CATALOGS = ("tpch", "tpcds")


# -- predicate refinement: range certificates for filter outputs ---------------


def _lit_scaled_point(lit, sym_type) -> Optional[int]:
    """A literal's exact value in the compared symbol's scaled units, or
    None when the conversion is not provably exact (float literals,
    downscales that would round)."""
    if not isinstance(lit, Literal) or lit.value is None:
        return None
    if not R.is_exact_type(sym_type) or not R.is_exact_type(lit.type):
        return None
    f = Analyzer()._literal(lit)
    if f.interval.lo is None or f.interval.lo != f.interval.hi:
        return None
    v = f.interval.lo
    ls = lit.type.scale if isinstance(lit.type, T.DecimalType) else 0
    ss = sym_type.scale if isinstance(sym_type, T.DecimalType) else 0
    k = ss - ls
    if k >= 0:
        return v * 10 ** k
    d = 10 ** (-k)
    if v % d:
        return None  # would round: not an exact representation
    return v // d


def _conjunct_refinements(c):
    """(symbol name, admitted Interval) facts one conjunct proves about
    surviving rows.  Comparisons are NULL-rejecting, so refined symbols
    are also proven non-null — the caller applies that too."""
    out = []

    def sym_and_lit(a, b):
        if isinstance(a, SymbolRef) and isinstance(b, Literal):
            return a, b, False
        if isinstance(b, SymbolRef) and isinstance(a, Literal):
            return b, a, True
        return None, None, False

    if isinstance(c, Call) and c.name in (
        "$eq", "$lt", "$le", "$gt", "$ge"
    ) and len(c.args) == 2:
        s, lit, flipped = sym_and_lit(*c.args)
        if s is None:
            return out
        v = _lit_scaled_point(lit, s.type)
        if v is None:
            return out
        op = c.name
        if flipped:
            op = FLIPPED_CMP[op]
        if op == "$eq":
            out.append((s.name, Interval.point(v)))
        elif op == "$lt":
            out.append((s.name, Interval(None, v - 1)))
        elif op == "$le":
            out.append((s.name, Interval(None, v)))
        elif op == "$gt":
            out.append((s.name, Interval(v + 1, None)))
        else:  # $ge
            out.append((s.name, Interval(v, None)))
    elif isinstance(c, SpecialForm) and c.form == Form.BETWEEN and len(c.args) == 3:
        s = c.args[0]
        if isinstance(s, SymbolRef):
            lo = _lit_scaled_point(c.args[1], s.type)
            hi = _lit_scaled_point(c.args[2], s.type)
            if lo is not None and hi is not None:
                out.append((s.name, Interval(lo, hi)))
    elif isinstance(c, SpecialForm) and c.form == Form.IN and len(c.args) >= 2:
        s = c.args[0]
        if isinstance(s, SymbolRef):
            vals = [_lit_scaled_point(x, s.type) for x in c.args[1:]]
            if all(v is not None for v in vals):
                out.append((s.name, Interval(min(vals), max(vals))))
    return out


def refine_env(env: Env, predicate) -> Env:
    """Filter-output fact refinement: rows surviving `predicate` provably
    satisfy its literal-comparison conjuncts, so each compared symbol's
    interval meets the admitted range and turns non-null (comparisons
    reject NULL).  Only exact facts are admitted — the same sources as
    the licensing passes — so downstream range certificates built on a
    refined env stay sound.  This is how PR 10's aggregation-input
    certificates extend to FILTER (and, through plan_env, join) outputs:
    a provably-narrow filtered column licenses narrower kernels."""
    refits: dict = {}
    for c in conjuncts(predicate):
        for name, iv in _conjunct_refinements(c):
            f = env.sym(name)
            if f is None or not f.tracked or not R.is_exact_type(f.type):
                continue
            cur = refits.get(name, f.interval)
            refits[name] = cur.intersect(iv)
    if not refits:
        return env
    syms = dict(env.symbols)
    for name, iv in refits.items():
        f = syms[name]
        syms[name] = Fact(f.type, iv, False, f.tracked)
    return Env(syms, env.channels)


def _scan_env(node, catalogs) -> Env:
    syms = {}
    stats_cols = {}
    try:
        conn = catalogs.get(node.handle.catalog)
        exact = node.handle.catalog in _EXACT_STATS_CATALOGS
        if exact:
            ts = conn.metadata().table_statistics(
                node.handle.schema, node.handle.table
            )
            if ts is not None:
                stats_cols = dict(ts.columns or {})
    except Exception:
        stats_cols = {}
    for sym, col in node.assignments:
        iv = None
        cs = stats_cols.get(col)
        if cs is not None:
            iv = R.stats_interval(sym.type, cs.low, cs.high)
        if iv is not None:
            nullable = bool(getattr(cs, "null_fraction", 0.0))
            syms[sym.name] = Fact(sym.type, iv, nullable, tracked=True)
        elif R.is_exact_type(sym.type) and not isinstance(
            sym.type, (T.ArrayType, T.MapType, T.RowType)
        ):
            syms[sym.name] = Fact(
                sym.type, R.type_interval(sym.type), True, tracked=True
            )
        else:
            syms[sym.name] = Fact.untracked(sym.type)
    env = Env(syms)
    if node.pushed_predicate is not None:
        # range certificates for FILTER OUTPUTS: rows a pushed predicate
        # admits provably satisfy it, so literal comparisons narrow the
        # surviving column facts (exactly like the licensing sources —
        # literals only, never estimates)
        env = refine_env(env, node.pushed_predicate)
    return env


def row_upper_bound(node, catalogs=None, _memo=None) -> Optional[int]:
    """A SOUND upper bound on the rows the node can ever produce, or None.

    Only hard facts are admitted: generator row counts (exact by
    construction for the builtin tpch/tpcds connectors), LIMIT/TopN counts,
    VALUES arity, and structural bounds (an inner/outer join emits at most
    |L|*|R| + |L| + |R| rows; a union the sum; an aggregation at most its
    input).  Everything else — estimates included — returns None."""
    from trino_tpu.planner import plan as P

    if _memo is None:
        _memo = {}
    key = id(node)
    if key in _memo:
        return _memo[key]
    _memo[key] = None  # cycle guard (plans are DAGs; shared subtrees fine)
    out: Optional[int] = None
    kids = [row_upper_bound(c, catalogs, _memo) for c in node.children]
    if isinstance(node, P.TableScanNode):
        try:
            if node.handle.catalog in _EXACT_STATS_CATALOGS:
                conn = catalogs.get(node.handle.catalog)
                ts = conn.metadata().table_statistics(
                    node.handle.schema, node.handle.table
                )
                if ts is not None and ts.row_count is not None:
                    out = int(ts.row_count)
        except Exception:
            out = None
    elif isinstance(node, P.ValuesNode):
        out = len(node.rows)
    elif isinstance(node, (P.LimitNode, P.TopNNode)):
        n = int(node.count)
        out = n if kids[0] is None else min(n, kids[0])
    elif isinstance(node, P.EnforceSingleRowNode):
        out = 1
    elif isinstance(node, P.JoinNode):
        l, r = kids[0], kids[1]
        if l is not None and r is not None:
            out = l * r + l + r  # outer-join null rows included
    elif isinstance(node, P.UnionNode):
        if all(k is not None for k in kids):
            out = sum(kids)
    elif isinstance(node, (P.UnnestNode, P.PatternRecognitionNode)):
        out = None  # may expand rows unboundedly
    elif isinstance(
        node,
        (
            P.FilterNode, P.ProjectNode, P.AggregationNode, P.SortNode,
            P.MarkDistinctNode, P.WindowNode, P.SampleNode, P.OutputNode,
            P.SemiJoinNode, P.ExchangeNode,
        ),
    ):
        out = kids[0]
    elif len(kids) == 1:
        out = kids[0]
    _memo[key] = out
    return out


def sound_rows_bound(node, catalogs=None) -> Optional[int]:
    """The canonical sound row bound: verify.capacity.rows_bound — which
    adds exact-filter selectivity and fanout-aware join bounds (a join
    with a proven-unique build key emits at most its probe side) on top of
    the structural `row_upper_bound`.  The capacity bounds are what let
    decimal-sum certificates license aggregations ABOVE joins."""
    try:
        from trino_tpu.verify.capacity import rows_bound

        b = rows_bound(node, catalogs)
    except Exception:
        b = None
    if b is not None:
        return b
    return row_upper_bound(node, catalogs)


def plan_env(node, catalogs=None, _memo=None, issues=None) -> Env:
    """Bottom-up symbol-fact derivation over a logical plan: what interval /
    nullability each output symbol of `node` is PROVEN to satisfy."""
    from trino_tpu.planner import plan as P

    if _memo is None:
        _memo = {}
    key = id(node)
    hit = _memo.get(key)
    if hit is not None:
        return hit
    _memo[key] = Env()  # cycle guard
    env = _plan_env(node, catalogs, _memo, issues)
    _memo[key] = env
    return env


def _merged_child_env(node, catalogs, memo, issues) -> Env:
    syms: dict = {}
    for c in node.children:
        syms.update(plan_env(c, catalogs, memo, issues).symbols)
    return Env(syms)


def _plan_env(node, catalogs, memo, issues) -> Env:
    from trino_tpu.planner import plan as P

    if isinstance(node, P.TableScanNode):
        return _scan_env(node, catalogs)
    src = _merged_child_env(node, catalogs, memo, issues)
    if isinstance(node, P.ProjectNode):
        syms = dict(src.symbols)
        out = {}
        for sym, e in node.assignments:
            a = Analyzer(Env(syms))
            fact = a.analyze(e)
            if issues is not None:
                issues.extend(a.issues)
            out[sym.name] = fact
        return Env(out)
    if isinstance(node, P.AggregationNode):
        rows = sound_rows_bound(node.source, catalogs)
        out = {s.name: src.sym(s.name) or Fact.untracked(s.type)
               for s in node.group_symbols}
        for out_sym, agg in node.aggregations:
            out[out_sym.name] = _agg_fact(out_sym, agg, src, rows)
        return Env(out)
    if isinstance(node, P.WindowNode):
        rows = sound_rows_bound(node.source, catalogs)
        out = dict(src.symbols)
        for out_sym, fn in node.functions:
            out[out_sym.name] = _window_fact(out_sym, fn, src, rows)
        return Env(out)
    if isinstance(node, P.UnionNode):
        out = {}
        for o, branches in zip(node.outputs, _union_inputs(node)):
            facts = [src.sym(b.name) for b in branches]
            if any(f is None for f in facts):
                out[o.name] = Fact.untracked(o.type)
                continue
            iv = facts[0].interval
            for f in facts[1:]:
                iv = iv.union(f.interval)
            out[o.name] = Fact(
                o.type, iv, any(f.nullable for f in facts),
                all(f.tracked for f in facts),
            )
        return Env(out)
    if isinstance(node, (P.JoinNode,)):
        # outer sides turn nullable; keep it simple and mark everything
        # from the non-preserved side nullable
        syms = dict(src.symbols)
        if getattr(node, "kind", "inner") != "inner":
            syms = {
                k: Fact(f.type, f.interval, True, f.tracked)
                for k, f in syms.items()
            }
        return Env(syms)
    if isinstance(node, P.SemiJoinNode):
        syms = dict(src.symbols)
        syms[node.mark.name] = Fact(T.BOOLEAN, Interval(0, 1), True)
        return Env(syms)
    if isinstance(node, P.ValuesNode):
        # rows hold raw python values in logical units
        out = {}
        for i, sym in enumerate(node.outputs):
            iv = None
            nullable = False
            tracked = R.is_exact_type(sym.type)
            for row in node.rows:
                v = row[i] if i < len(row) else None
                f = Analyzer()._literal(Literal(v, sym.type))
                iv = f.interval if iv is None else iv.union(f.interval)
                nullable = nullable or f.nullable
                tracked = tracked and f.tracked
            out[sym.name] = Fact(
                sym.type, iv if iv is not None else R.type_interval(sym.type),
                nullable, tracked,
            )
        return Env(out)
    if isinstance(node, P.FilterNode):
        # filter outputs carry refined range facts (see refine_env): the
        # predicate's literal comparisons narrow surviving symbols
        return refine_env(src, node.predicate)
    # structure-preserving nodes (sort/limit/exchange/output/...)
    return src


def _union_inputs(node):
    """Per-output list of input symbols across union branches."""
    cols = []
    for i, o in enumerate(node.outputs):
        cols.append([m[i] for m in node.source_symbols if i < len(m)])
    return cols


def _agg_fact(out_sym, agg, src: Env, rows: Optional[int]) -> Fact:
    name = agg.function
    ot = out_sym.type
    arg_fact = None
    if agg.args:
        a = Analyzer(src)
        arg_fact = a.analyze(agg.args[0])
    if name in ("count", "count_star"):
        hi = rows if rows is not None else None
        return Fact(ot, Interval(0, hi), False, tracked=rows is not None)
    if arg_fact is None:
        return Fact.untracked(ot)
    if name in ("min", "max", "any_value", "arbitrary", "avg"):
        iv = arg_fact.interval
        if isinstance(arg_fact.type, T.DecimalType) and isinstance(ot, T.DecimalType):
            iv = iv.scale_pow10(ot.scale - arg_fact.type.scale)
        elif not R.is_exact_type(ot):
            return Fact.untracked(ot)
        # avg of values in [lo, hi] stays in [lo, hi] (+1 rounding unit)
        if name == "avg":
            iv = iv.add(Interval(-1, 1))
        return Fact(ot, iv, True, arg_fact.tracked)
    if name == "sum" and rows is not None and arg_fact.tracked:
        iv = arg_fact.interval
        if isinstance(arg_fact.type, T.DecimalType) and isinstance(ot, T.DecimalType):
            iv = iv.scale_pow10(ot.scale - arg_fact.type.scale)
        elif isinstance(ot, T.DecimalType) or isinstance(arg_fact.type, T.DecimalType):
            return Fact.untracked(ot)
        if iv.bounded:
            return Fact(
                ot,
                Interval(min(iv.lo, 0) * rows, max(iv.hi, 0) * rows),
                True, tracked=True,
            )
    return Fact.untracked(ot)


def _window_fact(out_sym, fn, src: Env, rows: Optional[int]) -> Fact:
    ot = out_sym.type
    name = fn.name
    if name in ("row_number", "rank", "dense_rank", "ntile", "count",
                "count_star"):
        hi = rows if rows is not None else None
        return Fact(ot, Interval(0 if name.startswith("count") else 1, hi),
                    False, tracked=rows is not None)
    arg_fact = None
    if getattr(fn, "args", None):
        a0 = fn.args[0]
        arg_fact = Analyzer(src).analyze(a0)
    if arg_fact is not None and name in (
        "min", "max", "first_value", "last_value", "nth_value", "lag",
        "lead", "avg",
    ):
        iv = arg_fact.interval
        if isinstance(arg_fact.type, T.DecimalType) and isinstance(ot, T.DecimalType):
            iv = iv.scale_pow10(ot.scale - arg_fact.type.scale)
        elif not R.is_exact_type(ot):
            return Fact.untracked(ot)
        if name == "avg":
            iv = iv.add(Interval(-1, 1))
        return Fact(ot, iv, True, arg_fact.tracked)
    if (
        name == "sum" and arg_fact is not None and rows is not None
        and arg_fact.tracked and arg_fact.interval.bounded
    ):
        iv = arg_fact.interval
        if isinstance(arg_fact.type, T.DecimalType) and isinstance(ot, T.DecimalType):
            iv = iv.scale_pow10(ot.scale - arg_fact.type.scale)
        if iv.bounded:
            return Fact(
                ot, Interval(min(iv.lo, 0) * rows, max(iv.hi, 0) * rows),
                True, tracked=True,
            )
    return Fact.untracked(ot)


# -- certificates: the planner-facing licensing API ----------------------------


def sum_certificate(
    expr: Expr, env: Env, rows_bound: Optional[int],
) -> Optional[RangeCertificate]:
    """Range certificate for an aggregation/window SUM input expression, or
    None when no admissible proof exists.  `env` binds the expression's free
    references (symbols or channels) to facts; `rows_bound` bounds the total
    contributing rows across the whole query (see row_upper_bound)."""
    try:
        fact, _ = analyze_expr(expr, env)
    except Exception:
        return None
    if not fact.tracked or not R.is_exact_type(fact.type):
        return None
    t = fact.type
    scale = t.scale if isinstance(t, T.DecimalType) else 0
    prov = ["expr:" + _expr_brief(expr)]
    if rows_bound is not None:
        prov.append(f"rows:{rows_bound}")
    return R.certificate(fact.interval, scale, rows_bound, prov)


def _expr_brief(e: Expr) -> str:
    s = repr(e)
    return s if len(s) <= 120 else s[:117] + "..."


def channel_env_for(symbols, sym_env: Env) -> Env:
    """Adapter: symbol-keyed env -> channel-keyed env for a layout."""
    return Env.for_layout(symbols, sym_env)


def license_decimal_sums(plan, catalogs=None) -> int:
    """The planner-facing licensing pass: walk the optimized logical plan
    and attach a proof-licensed `sum_bound` to every decimal sum/avg
    Aggregation / window function whose input expression has a range
    certificate proving ALL partial sums fit int64.  Runs once at the end
    of plan optimization — before fragmentation — so the local planner,
    the distributed partial/final split, and the window operator all read
    the same proof off the plan node.  Returns the number licensed."""
    from trino_tpu.planner import plan as P

    n = 0
    env_memo: dict = {}
    for node in _walk_plan(plan):
        if isinstance(node, P.AggregationNode):
            rows = sound_rows_bound(node.source, catalogs)
            if rows is None:
                continue
            env = plan_env(node.source, catalogs, env_memo)
            for out_sym, agg in node.aggregations:
                if agg.function not in ("sum", "avg") or not agg.args:
                    continue
                # the sum STATE is Int128 (decimal(38, s)) for every
                # decimal input — avg included, whatever its output type
                # (_state_types mirrors DecimalSumAggregation)
                if not isinstance(agg.args[0].type, T.DecimalType):
                    continue
                cert = sum_certificate(agg.args[0], env, rows)
                if cert is None:
                    continue
                b = cert.licensed_i64_sum_bound()
                if b is not None:
                    agg.sum_bound = b
                    n += 1
        elif isinstance(node, P.WindowNode):
            rows = sound_rows_bound(node.source, catalogs)
            if rows is None:
                continue
            env = plan_env(node.source, catalogs, env_memo)
            for out_sym, fn in node.functions:
                if fn.name not in ("sum", "avg") or not fn.args:
                    continue
                at = fn.args[0].type
                if not isinstance(at, T.DecimalType):
                    continue
                cert = sum_certificate(fn.args[0], env, rows)
                if cert is None:
                    continue
                b = cert.licensed_i64_sum_bound()
                if b is not None:
                    fn.sum_bound = b
                    n += 1
    return n


# -- the sweep: every expression of every TPC-H + TPC-DS plan ------------------


#: expression positions per node type: (description, expr) pairs
def _node_exprs(node):
    from trino_tpu.planner import plan as P

    if isinstance(node, P.TableScanNode):
        if node.pushed_predicate is not None:
            yield "pushed_predicate", node.pushed_predicate
    elif isinstance(node, P.FilterNode):
        yield "predicate", node.predicate
    elif isinstance(node, P.ProjectNode):
        for sym, e in node.assignments:
            yield f"project:{sym.name}", e
    elif isinstance(node, P.AggregationNode):
        for out_sym, agg in node.aggregations:
            for a in agg.args:
                yield f"agg:{out_sym.name}", a
            if agg.filter is not None:
                yield f"agg_filter:{out_sym.name}", agg.filter
    elif isinstance(node, P.JoinNode):
        if node.filter is not None:
            yield "join_filter", node.filter
    elif isinstance(node, P.SemiJoinNode):
        if node.filter is not None:
            yield "semijoin_filter", node.filter
    elif isinstance(node, P.UnnestNode):
        for sym, e in node.unnest:
            yield f"unnest:{sym.name}", e


def _walk_plan(node, _seen=None):
    if _seen is None:
        _seen = set()
    if id(node) in _seen:
        return
    _seen.add(id(node))
    yield node
    for c in node.children:
        yield from _walk_plan(c, _seen)


def numeric_safety_baseline(root: str = ".") -> dict:
    """{rule:signature -> justification} from tools/lint_baseline.json.

    DELIBERATE twin of tools/lint_tpu.numeric_safety_baseline: the lint
    must stay stdlib-only (the dependency-free CI lint job cannot import
    trino_tpu), so the two passes share the JSON contract, not code —
    change the file location / key / error handling in BOTH places."""
    import json
    import os

    path = os.path.join(root, "tools", "lint_baseline.json")
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return dict(json.load(fh).get("numeric_safety") or {})
    except (OSError, ValueError):
        return {}


@dataclass
class SweepResult:
    proven: int = 0
    baselined: int = 0
    violations: list = field(default_factory=list)  # (where, Issue)
    used_baseline: set = field(default_factory=set)
    expressions: int = 0


def sweep_plan(plan, catalogs, baseline: dict, result: SweepResult,
               where: str, verbose: bool = False) -> None:
    issues_sink: list = []
    env_memo: dict = {}
    for node in _walk_plan(plan):
        src_env = Env()
        if node.children:
            syms: dict = {}
            for c in node.children:
                syms.update(
                    plan_env(c, catalogs, env_memo, issues_sink).symbols
                )
            src_env = Env(syms)
        elif hasattr(node, "assignments") and hasattr(node, "handle"):
            src_env = _scan_env(node, catalogs)
        for slot, e in _node_exprs(node):
            result.expressions += 1
            a = Analyzer(src_env)
            try:
                a.analyze(e)
            except Exception as exc:  # analyzer must never kill the sweep
                a.issues.append(Issue(
                    "analyzer-error", type(exc).__name__, str(exc)[:200]
                ))
            if not a.issues:
                result.proven += 1
                continue
            unbase = []
            for iss in a.issues:
                if iss.key() in baseline:
                    result.used_baseline.add(iss.key())
                else:
                    unbase.append(iss)
            if not unbase:
                result.baselined += 1
            else:
                for iss in unbase:
                    result.violations.append((f"{where}/{slot}", iss))
                if verbose:
                    for iss in unbase:
                        print(f"VIOLATION {where}/{slot}: {iss}")


def verify_benchmarks(verbose: bool = False, root: str = ".") -> SweepResult:
    """Walk every expression of every TPC-H + TPC-DS plan through the
    analyzer; classify each as PROVEN-SAFE / BASELINED / VIOLATION."""
    from trino_tpu.runtime.runner import LocalQueryRunner

    baseline = numeric_safety_baseline(root)
    result = SweepResult()
    suites = (
        ("tpch", "tiny", "trino_tpu.connectors.tpch.queries"),
        ("tpcds", "tiny", "trino_tpu.connectors.tpcds.queries"),
    )
    for catalog, schema, mod in suites:
        import importlib

        queries = importlib.import_module(mod).QUERIES
        r = LocalQueryRunner(catalog=catalog, schema=schema)
        for q in sorted(queries):
            plan = r.create_plan(queries[q])
            sweep_plan(
                plan, r.catalogs, baseline, result,
                f"{catalog}:{q}", verbose,
            )
    result.violations.sort(key=lambda v: (v[0], v[1].key()))
    return result


def main() -> int:  # pragma: no cover - CLI entry
    import argparse

    ap = argparse.ArgumentParser(
        description="numeric-safety sweep over all TPC-H + TPC-DS plan "
        "expressions (abstract interpretation of dtype/scale/range/validity)"
    )
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--root", default=".")
    ap.add_argument(
        "--check-stale", action="store_true",
        help="FAIL when a rule:signature baseline entry no longer matches "
        "any live sweep finding (the stale-baseline detector, on in CI — "
        "the twin of tools/lint_tpu.py --check-stale for the AST keys)",
    )
    args = ap.parse_args()
    res = verify_benchmarks(args.verbose, root=args.root)
    # path-prefixed keys belong to the AST pass in tools/lint_tpu.py (its
    # own staleness check covers them); only rule:signature keys are ours
    stale = {
        k for k in numeric_safety_baseline(args.root)
        if not k.startswith("trino_tpu/")
    } - res.used_baseline
    for where, iss in res.violations:
        print(f"VIOLATION {where}: {iss}")
        print(f"  baseline key: {iss.key()!r}")
    for k in sorted(stale):
        print(
            f"{'STALE' if args.check_stale else 'note'}: numeric_safety "
            f"baseline entry {k!r} has no live finding — ratchet "
            "tools/lint_baseline.json down"
        )
    print(
        f"numeric-safety: {res.expressions} expressions — "
        f"{res.proven} PROVEN-SAFE, {res.baselined} BASELINED, "
        f"{len(res.violations)} VIOLATION(s)"
    )
    if res.violations:
        return 1
    return 1 if (args.check_stale and stale) else 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
