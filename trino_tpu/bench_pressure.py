"""Memory-pressure bench probe (the `pressure` section of BENCH_EXTRA's
mesh schemas, gated by tools/compare_bench.py).

The degradation proof ISSUE'd by the escalation ladder: Q18 — whose build
side and group-by state dwarf a constrained pool — must complete under a
pool limit smaller than its unconstrained peak, in k > 1 partition waves
with filesystem-SPI spill, answering exactly the unconstrained local
oracle's rows; and the unconstrained runs before it must have recorded
ZERO waves, spill, and revocations (degradation is free without pressure).

Shared by `bench.py --mesh` (inline in its child process) and
`tools/pressure_bench.py` (standalone recorder).
"""

from __future__ import annotations

import time


def pressure_counters() -> dict:
    """Process totals of the degradation counters."""
    from trino_tpu.telemetry.metrics import (
        MEMORY_WAVE_OPERATORS,
        memory_revocations_counter,
        memory_waves_counter,
        spill_bytes_counter,
    )

    waves = memory_waves_counter()
    return {
        "memory_waves_total": sum(
            int(waves.value((op,))) for op in MEMORY_WAVE_OPERATORS
        ),
        "spill_bytes_total": int(spill_bytes_counter().value()),
        "memory_revocations_total": int(
            memory_revocations_counter().value()
        ),
    }


def _delta(after: dict, before: dict) -> dict:
    return {k: after[k] - before[k] for k in after}


def run_pressure(local, dist, sql: str) -> dict:
    """Run the pressure probe; `local`/`dist` are warmed runners whose
    process has already executed the unconstrained benched queries (their
    counter totals are the zero-cost-when-idle evidence)."""
    from trino_tpu.runtime.lifecycle import set_memory_pool_limit

    unconstrained = pressure_counters()
    # unconstrained oracle + its peak reservation (the pool limit derives
    # from MEASURED peak, so the probe scales with schema size)
    t0 = time.perf_counter()
    oracle = sorted(map(str, local.execute(sql).rows))
    oracle_wall = time.perf_counter() - t0
    peak = int(getattr(local, "_last_peak_memory", 0))
    limit = max(peak // 8, 1 << 20)
    out: dict = {
        "unconstrained": unconstrained,
        "unconstrained_peak_bytes": peak,
        "unconstrained_local_wall_s": round(oracle_wall, 4),
        "pool_limit_bytes": limit,
    }

    def constrained(runner, name: str) -> dict:
        before = pressure_counters()
        set_memory_pool_limit(limit)
        try:
            t0 = time.perf_counter()
            rows = sorted(map(str, runner.execute(sql).rows))
            wall = time.perf_counter() - t0
        finally:
            set_memory_pool_limit(0)
        d = _delta(pressure_counters(), before)
        return {
            "wall_s": round(wall, 4),
            "rows_match": rows == oracle,
            "waves": d["memory_waves_total"],
            "spill_bytes": d["spill_bytes_total"],
            "revocations": d["memory_revocations_total"],
        }

    out["local"] = constrained(local, "local")
    if dist is not None:
        out["mesh"] = constrained(dist, "mesh")
    return out
