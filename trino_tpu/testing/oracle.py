"""Pandas materialization of connector tables — the correctness oracle.

Reference role: testing/trino-testing/.../H2QueryRunner.java + QueryAssertions:
expected results come from an independent implementation over identical data.
Decimals are materialized as float (tests use tolerances for decimal results,
mirroring QueryAssertions' approximate assertions) plus a parallel *_cents
int column when exactness matters.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np
import pandas as pd

from trino_tpu import types as T
from trino_tpu.connectors.api import Connector, TableHandle


def connector_table_to_pandas(
    connector: Connector, schema: str, table: str, columns=None
) -> pd.DataFrame:
    meta = connector.metadata().table_metadata(schema, table)
    names = columns or [c.name for c in meta.columns]
    handle = TableHandle(connector.name, schema, table)
    frames = []
    for split in connector.splits(handle, target_splits=1 << 30):
        src = connector.page_source(split, names)
        for page in src.pages():
            cols = {}
            for cm_name, cd in zip(names, page):
                t = meta.column(cm_name).type
                if cd.dictionary is not None:
                    vals = np.asarray(cd.dictionary.decode(cd.values), dtype=object)
                elif isinstance(t, T.DecimalType):
                    vals = cd.values.astype(np.float64) / t.scale_factor
                    cols[cm_name + "__cents"] = cd.values.astype(np.int64)
                elif t is T.DATE:
                    vals = np.array("1970-01-01", dtype="datetime64[D]") + cd.values
                else:
                    vals = cd.values
                if cd.valid is not None:
                    vals = np.where(cd.valid, vals, None)
                cols[cm_name] = vals
            frames.append(pd.DataFrame(cols))
    if not frames:
        return pd.DataFrame({n: [] for n in names})
    return pd.concat(frames, ignore_index=True)


@lru_cache(maxsize=16)
def tpch_pandas(schema: str, table: str) -> pd.DataFrame:
    """Cached full-table pandas frame for a tpch schema (tests: tiny/sf1)."""
    from trino_tpu.connectors.tpch import TpchConnector

    return connector_table_to_pandas(TpchConnector(), schema, table)
