"""Test harness utilities (reference: core/trino-main/.../testing and
testing/trino-testing).

The pandas oracle plays H2's role from the reference's QueryAssertions:
an independent engine over the *same* connector data that expected results
are computed against.
"""

from trino_tpu.testing.oracle import connector_table_to_pandas, tpch_pandas

__all__ = ["connector_table_to_pandas", "tpch_pandas"]
