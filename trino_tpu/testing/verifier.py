"""Query verifier: run a suite against a control and a test engine, compare.

Reference role: service/trino-verifier (VerifyCommand / Validator.java —
pairs of JDBC endpoints, row-set comparison with floating-point tolerance,
per-query verdicts).  Engines here are anything with `.execute(sql)` → a
result with `.rows` (LocalQueryRunner, DistributedQueryRunner, dbapi-wrapped
HTTP endpoints), so control can be the local engine and test a remote one.
"""

from __future__ import annotations

import time
import traceback
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass
class VerifierResult:
    query_id: str
    sql: str
    status: str  # MATCH | MISMATCH | CONTROL_ERROR | TEST_ERROR
    control_wall_s: float = 0.0
    test_wall_s: float = 0.0
    detail: str = ""


@dataclass
class VerifierReport:
    results: list = field(default_factory=list)

    @property
    def matched(self) -> int:
        return sum(1 for r in self.results if r.status == "MATCH")

    @property
    def failed(self) -> list:
        return [r for r in self.results if r.status != "MATCH"]

    def summary(self) -> str:
        lines = [
            f"verified {len(self.results)} queries: {self.matched} match, "
            f"{len(self.failed)} fail"
        ]
        for r in self.failed:
            lines.append(f"  {r.query_id}: {r.status} {r.detail[:200]}")
        return "\n".join(lines)


class Verifier:
    def __init__(
        self,
        control,
        test,
        float_tolerance: float = 1e-9,
        ordered: bool = False,
    ):
        self.control = control
        self.test = test
        self.float_tolerance = float_tolerance
        self.ordered = ordered

    def run(self, queries: dict | Sequence) -> VerifierReport:
        if not isinstance(queries, dict):
            queries = {f"q{i}": q for i, q in enumerate(queries)}
        report = VerifierReport()
        for qid, sql in queries.items():
            report.results.append(self._one(str(qid), sql))
        return report

    def _one(self, qid: str, sql: str) -> VerifierResult:
        t0 = time.perf_counter()
        try:
            control_rows = _rows(self.control.execute(sql))
        except Exception:
            return VerifierResult(
                qid, sql, "CONTROL_ERROR",
                detail=traceback.format_exc(limit=2),
            )
        t1 = time.perf_counter()
        try:
            test_rows = _rows(self.test.execute(sql))
        except Exception:
            return VerifierResult(
                qid, sql, "TEST_ERROR",
                control_wall_s=t1 - t0,
                detail=traceback.format_exc(limit=2),
            )
        t2 = time.perf_counter()
        ok, detail = self._compare(control_rows, test_rows)
        return VerifierResult(
            qid,
            sql,
            "MATCH" if ok else "MISMATCH",
            control_wall_s=t1 - t0,
            test_wall_s=t2 - t1,
            detail=detail,
        )

    # -- comparison (Validator.java's resultsMatch) --------------------------

    def _compare(self, control, test) -> tuple:
        if len(control) != len(test):
            return False, f"row count {len(control)} != {len(test)}"
        c, t = list(control), list(test)
        if not self.ordered:
            c, t = sorted(c, key=_row_key), sorted(t, key=_row_key)
        for i, (rc, rt) in enumerate(zip(c, t)):
            if len(rc) != len(rt):
                return False, f"row {i}: width {len(rc)} != {len(rt)}"
            for j, (vc, vt) in enumerate(zip(rc, rt)):
                if not self._value_eq(vc, vt):
                    return False, f"row {i} col {j}: {vc!r} != {vt!r}"
        return True, ""

    def _value_eq(self, a, b) -> bool:
        if a is None or b is None:
            return a is None and b is None
        if isinstance(a, float) or isinstance(b, float):
            try:
                fa, fb = float(a), float(b)
            except (TypeError, ValueError):
                return a == b
            scale = max(abs(fa), abs(fb), 1.0)
            return abs(fa - fb) <= self.float_tolerance * scale
        return a == b


def _rows(result):
    rows = getattr(result, "rows", result)
    return [tuple(r) for r in rows]


def _row_key(row):
    return tuple((v is None, str(type(v)), str(v)) for v in row)
