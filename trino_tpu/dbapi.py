"""PEP 249 (DB-API 2.0) driver over the statement protocol.

Reference role: client/trino-jdbc (TrinoDriver/TrinoResultSet, 20.4k LoC of
JDBC 4 over the HTTP protocol) — the Python-native equivalent of "standard
database connectivity on top of the client protocol" is DB-API, so this
module plays the JDBC driver's part: connect() -> Connection -> Cursor with
execute/fetchone/fetchmany/fetchall/description, driven through the same
/v1/statement + nextUri protocol as the CLI (client.py).

An in-process mode (connect(runner=...)) binds a cursor directly to a
LocalQueryRunner — the counterpart of the JDBC driver's embedded/testing
path (LocalQueryRunner-backed connections in trino-testing).
"""

from __future__ import annotations

from typing import Optional, Sequence

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: Optional[list] = None
        self._pos = 0
        self.description = None
        self.rowcount = -1

    # -- PEP 249 --------------------------------------------------------------

    def execute(self, operation: str, parameters: Sequence = ()) -> "Cursor":
        if self._conn._closed:
            raise InterfaceError("cursor on a closed connection")
        sql = _substitute(operation, parameters)
        try:
            names, rows, types = self._conn._run(sql)
        except Error:
            raise
        except Exception as e:
            raise DatabaseError(str(e)) from e
        self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        self.description = [
            (n, t, None, None, None, None, None)
            for n, t in zip(names, types)
        ]
        return self

    def executemany(self, operation: str, seq_of_parameters) -> "Cursor":
        for p in seq_of_parameters:
            self.execute(operation, p)
        return self

    def fetchone(self):
        if self._rows is None:
            raise InterfaceError("no query executed")
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None):
        n = size or self.arraysize
        out = self._rows[self._pos : self._pos + n] if self._rows else []
        self._pos += len(out)
        return out

    def fetchall(self):
        if self._rows is None:
            raise InterfaceError("no query executed")
        out = self._rows[self._pos :]
        self._pos = len(self._rows)
        return out

    def close(self) -> None:
        self._rows = None

    def setinputsizes(self, sizes) -> None:  # optional per PEP 249
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass

    def __iter__(self):
        while True:
            r = self.fetchone()
            if r is None:
                return
            yield r


class Connection:
    def __init__(self, url: Optional[str] = None, runner=None):
        if runner is None and url is None:
            raise InterfaceError("connect() needs a url or a runner")
        self._runner = runner
        self._client = None
        if runner is None:
            from trino_tpu.client import Client

            self._client = Client(url)
        self._closed = False

    def _run(self, sql: str):
        if self._runner is not None:
            res = self._runner.execute(sql)
            return (
                list(res.column_names),
                list(res.rows),
                [getattr(t, "name", str(t)) for t in res.types],
            )
        names, rows = self._client.execute(sql)
        return list(names), [tuple(r) for r in rows], [None] * len(names)

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def commit(self) -> None:
        if self._runner is not None and getattr(
            self._runner, "in_transaction", False
        ):
            self._runner.execute("commit")

    def rollback(self) -> None:
        if self._runner is not None and getattr(
            self._runner, "in_transaction", False
        ):
            self._runner.execute("rollback")

    def close(self) -> None:
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(url: Optional[str] = None, runner=None) -> Connection:
    """connect("http://host:port") for the protocol path, or
    connect(runner=LocalQueryRunner(...)) for the embedded path."""
    return Connection(url, runner)


def _split_placeholders(operation: str) -> list:
    """Split on '?' placeholders, ignoring ones inside '...' string literals
    (with '' escapes), "..." quoted identifiers, and -- or /* */ comments."""
    parts, buf, quote = [], [], None
    i, n = 0, len(operation)
    while i < n:
        ch = operation[i]
        if quote is not None:
            buf.append(ch)
            if ch == quote:
                if i + 1 < n and operation[i + 1] == quote:  # '' escape
                    buf.append(operation[i + 1])
                    i += 1
                else:
                    quote = None
        elif ch == "-" and operation.startswith("--", i):
            j = operation.find("\n", i)
            j = n if j < 0 else j
            buf.append(operation[i:j])
            i = j - 1
        elif ch == "/" and operation.startswith("/*", i):
            j = operation.find("*/", i)
            j = n if j < 0 else j + 2
            buf.append(operation[i:j])
            i = j - 1
        elif ch in ("'", '"'):
            quote = ch
            buf.append(ch)
        elif ch == "?":
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    parts.append("".join(buf))
    return parts


def _substitute(operation: str, parameters: Sequence) -> str:
    """qmark substitution with SQL literal quoting."""
    if not parameters:
        return operation
    parts = _split_placeholders(operation)
    if len(parts) - 1 != len(parameters):
        raise InterfaceError(
            f"statement has {len(parts) - 1} placeholders, "
            f"{len(parameters)} parameters given"
        )
    out = [parts[0]]
    for p, rest in zip(parameters, parts[1:]):
        out.append(_literal(p))
        out.append(rest)
    return "".join(out)


def _literal(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    import datetime
    import decimal

    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, datetime.date):
        return f"date '{v.isoformat()}'"
    raise InterfaceError(f"unsupported parameter type {type(v).__name__}")
