"""Typed engine configuration (the airlift ``@Config`` analog, SURVEY §5.6).

PR 5 left every robustness knob a process-wide constant: the circuit-breaker
trip threshold (3) and half-open cooldown (5 s) were baked into
`runtime/retry.py`, the HTTP-tier timeouts into `runtime/lifecycle.py`, and
the remote retry budgets into `parallel/remote.py`.  This module replaces
them with declarative config classes — one dataclass per subsystem, every
field carrying its properties key — loaded from a ``config.properties``
file (the launcher etc/ layout `runtime/config.py` already parses) with
environment-variable overrides, exactly the reference's
``io.airlift.configuration`` binding order.

Resolution order for a knob (first hit wins):

  1. environment: ``TRINO_TPU_<KEY>`` with ``.``/``-`` -> ``_`` and
     uppercased (``breaker.failure-threshold`` ->
     ``TRINO_TPU_BREAKER_FAILURE_THRESHOLD``);
  2. per-catalog override: ``<key>@<catalog>`` where ``<catalog>`` is the
     EXACT catalog name a resolution is scoped to (catalog names are clean
     identifiers, so exact match — no substring ambiguity with worker
     tokens);
  3. per-worker override: ``<key>@<token>`` where ``<token>`` is a
     substring of the worker id/url (``breaker.failure-threshold@8123=5``
     tunes only the worker whose url contains ``8123``);
  4. the properties file: ``<key>=<value>``;
  5. the dataclass default — the PR 5 constants, so behaviour is unchanged
     when nothing is set.

The process-wide instance is ``get_config()``; ``install_config`` /
``load_config`` swap it (``runtime/config.load_etc`` installs one from
``etc/config.properties`` automatically) and ``reset_config`` restores
defaults for tests.  Consumers read through the accessor at USE time, so a
late install still takes effect (breakers are created lazily per worker).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field, fields
from typing import Optional

ENV_PREFIX = "TRINO_TPU_"


def knob(default, key: str, help: str = ""):
    """A config field bound to a properties key (the ``@Config`` marker)."""
    return field(default=default, metadata={"key": key, "help": help})


def _env_name(key: str) -> str:
    return ENV_PREFIX + key.upper().replace(".", "_").replace("-", "_")


def _coerce(value: str, typ: type):
    if typ is bool:
        low = str(value).strip().lower()
        if low in ("true", "yes", "on", "1"):
            return True
        if low in ("false", "no", "off", "0"):
            return False
        raise ValueError(f"not a boolean: {value!r}")
    return typ(value)


class ConfigSection:
    """Base for typed config dataclasses: `from_properties` resolves every
    `knob()` field through env > per-worker override > properties > default."""

    @classmethod
    def from_properties(cls, props: Optional[dict] = None, env=None,
                        worker: Optional[str] = None,
                        catalog: Optional[str] = None):
        props = props or {}
        env = os.environ if env is None else env
        values = {}
        for f in fields(cls):
            key = f.metadata.get("key")
            if key is None:
                continue
            typ = type(f.default)
            raw = env.get(_env_name(key))
            if raw is None and catalog is not None:
                raw = props.get(f"{key}@{catalog}")
            if raw is None and worker is not None:
                raw = _worker_override(props, key, worker)
            if raw is None:
                raw = props.get(key)
            if raw is None:
                continue
            try:
                values[f.name] = _coerce(raw, typ)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"bad value for config key {key!r}: {raw!r}"
                ) from e
        return cls(**values)

    def describe(self) -> list:
        """[(properties key, value, help)] — the config's SQL/debug view."""
        out = []
        for f in fields(self):
            key = f.metadata.get("key")
            if key is not None:
                out.append((key, getattr(self, f.name), f.metadata.get("help", "")))
        return out


def _worker_override(props: dict, key: str, worker: str) -> Optional[str]:
    """``<key>@<token>`` entries whose token occurs in the worker id win
    over the base key (longest matching token wins — the most specific
    override).  Tokens are substrings because worker ids are urls and the
    properties syntax cannot carry ``:`` inside a key."""
    best = None
    best_len = -1
    prefix = key + "@"
    for k, v in props.items():
        if not k.startswith(prefix):
            continue
        token = k[len(prefix):]
        if token and token in worker and len(token) > best_len:
            best, best_len = v, len(token)
    return best


# -- subsystem sections --------------------------------------------------------


@dataclass
class BreakerConfig(ConfigSection):
    """Per-worker circuit breakers on the multi-host HTTP tier (PR 5's
    fixed knobs, now loadable; reference: the failure-detection half of
    HttpRemoteTask)."""

    failure_threshold: int = knob(
        3, "breaker.failure-threshold",
        "consecutive failures before a worker's breaker trips OPEN",
    )
    cooldown_s: float = knob(
        5.0, "breaker.cooldown",
        "seconds an OPEN breaker holds traffic before one half-open probe",
    )


@dataclass
class HeartbeatConfig(ConfigSection):
    """Coordinator-side heartbeat failure detection (reference:
    failuredetector/HeartbeatFailureDetector)."""

    interval_s: float = knob(
        1.0, "heartbeat.interval",
        "seconds between failure-detector probe rounds",
    )
    miss_threshold: int = knob(
        3, "heartbeat.miss-threshold",
        "consecutive missed probes before a worker is declared DEAD",
    )
    probe_timeout_s: float = knob(
        5.0, "heartbeat.probe-timeout",
        "per-probe HTTP timeout (GET /v1/info)",
    )


@dataclass
class LifecycleConfig(ConfigSection):
    """HTTP-tier timeout bounds (PR 5's lifecycle constants): every socket
    wait is additionally capped by the executing query's remaining run time
    via `lifecycle.request_timeout`."""

    request_timeout_s: float = knob(
        600.0, "lifecycle.request-timeout",
        "default per-request HTTP bound when no query deadline caps it",
    )
    submit_timeout_s: float = knob(
        60.0, "lifecycle.submit-timeout",
        "task submission POST bound (small body, worker answers fast)",
    )
    cancel_timeout_s: float = knob(
        10.0, "lifecycle.cancel-timeout",
        "best-effort task cancel DELETE bound",
    )
    probe_timeout_s: float = knob(
        5.0, "lifecycle.probe-timeout",
        "worker liveness probe bound (GET /v1/info)",
    )


@dataclass
class RemoteConfig(ConfigSection):
    """Coordinator-side remote scheduling knobs (parallel/remote.py — the
    module the no-module-level-knob lint now keeps literal-free)."""

    submit_attempts: int = knob(
        3, "remote.submit-attempts",
        "transient-submit retries against one worker before it is "
        "declared gone (REFUSED skips them)",
    )
    fetch_attempts: int = knob(
        3, "remote.fetch-attempts",
        "transient result-fetch retries against the SAME worker before "
        "task replacement",
    )
    probe_ttl_s: float = knob(
        15.0, "remote.probe-ttl",
        "seconds a cached liveness-probe verdict stays fresh",
    )
    backoff_base_s: float = knob(
        0.05, "remote.backoff-base",
        "full-jitter backoff base for submit/fetch retries",
    )
    backoff_cap_s: float = knob(
        1.0, "remote.backoff-cap",
        "full-jitter backoff ceiling for submit/fetch retries",
    )
    max_replans: int = knob(
        8, "remote.max-replans",
        "mesh-shrink re-planning attempts per query before giving up",
    )
    max_task_retries: int = knob(
        4, "remote.max-task-retries",
        "same-plan recovery attempts per query under "
        "fault_tolerant_execution (lost tasks re-run on survivors, "
        "spooled fragments resume) before classifying the mesh as shrunk "
        "below the plan's requirements and re-planning",
    )


@dataclass
class WorkerConfig(ConfigSection):
    """Worker-server execution knobs (server/worker.py)."""

    max_concurrent_tasks: int = knob(
        4, "worker.max-concurrent-tasks",
        "tasks running concurrently on one worker (TaskExecutor slots)",
    )
    result_wait_s: float = knob(
        600.0, "worker.result-wait",
        "result long-poll bound when a task carries no deadline",
    )
    status_wait_s: float = knob(
        1.0, "worker.status-wait",
        "task status long-poll bound",
    )
    drain_task_wait_s: float = knob(
        600.0, "worker.drain-task-wait",
        "max seconds graceful drain waits on each running task",
    )
    drain_grace_s: float = knob(
        5.0, "worker.drain-grace",
        "seconds a drained server lingers after its last task finishes so "
        "downstream consumers can still pull its results",
    )
    coordinator_url: str = knob(
        "", "worker.coordinator-url",
        "coordinator base url a starting worker announces itself to "
        "(PUT /v1/worker/register) so a restarted worker resurrects its "
        "membership entry without operator action; empty = no announce",
    )


@dataclass
class CoordinatorConfig(ConfigSection):
    """Coordinator protocol knobs (server/coordinator.py)."""

    result_page_rows: int = knob(
        4096, "coordinator.result-page-rows",
        "rows per paged statement response",
    )
    poll_wait_s: float = knob(
        1.0, "coordinator.poll-wait",
        "statement/trace long-poll bound",
    )


@dataclass
class CompileCacheConfig(ConfigSection):
    """Persistent on-disk XLA compilation cache (JAX's native
    ``jax_compilation_cache_dir``), wired through the filesystem SPI
    (trino_tpu/filesystem.py).  `spmd.TRACE_CACHE` is process-local and
    dies with the process, but the XLA compile — the expensive half of a
    cold start — can be reloaded from disk: a restarted worker re-traces
    but skips recompiles.  Remote object-store locations degrade to a
    loud no-op until the scheme is implemented (runtime/prewarm.
    enable_persistent_compile_cache).  The cache is per-host: XLA CPU
    entries embed machine features, so point workers at host-local dirs."""

    dir: str = knob(
        "", "compile-cache.dir",
        "on-disk XLA compilation cache location (empty = disabled); "
        "resolved through the filesystem SPI, so file:// and plain paths "
        "work and object-store schemes fail loudly at configuration time",
    )
    enabled: bool = knob(
        True, "compile-cache.enabled",
        "master switch for the persistent compile cache (a set dir can be "
        "disabled without unsetting it)",
    )
    min_compile_time_s: float = knob(
        0.0, "compile-cache.min-compile-time",
        "only compiles at least this slow persist (0 = persist everything; "
        "engine SPMD programs are all worth caching)",
    )
    min_entry_size_bytes: int = knob(
        -1, "compile-cache.min-entry-size-bytes",
        "only cache entries at least this large persist (-1 = everything)",
    )


@dataclass
class PrewarmConfig(ConfigSection):
    """AOT prewarm executor (runtime/prewarm.py): replay a persisted
    workload manifest at server start / after mesh growth so the first
    real query finds every (step, bucket, mesh) key already traced."""

    manifest_path: str = knob(
        "", "prewarm.manifest-path",
        "workload-manifest location (filesystem SPI; empty = prewarm off): "
        "SQL replay set + cap_history seed + closure watermark",
    )
    on_start: bool = knob(
        True, "prewarm.on-start",
        "replay the manifest in a background thread at coordinator/worker "
        "server start",
    )
    on_grow: bool = knob(
        True, "prewarm.on-grow",
        "replay the manifest after add_worker grows the mesh, re-tracing "
        "at the NEW mesh signature before the next query arrives",
    )


@dataclass
class DictionaryConfig(ConfigSection):
    """Global dictionary service (runtime/dictionary_service.py): the
    coordinator-owned versioned code assignment that makes varchar keys
    first-class in exchanges, co-located joins, and capacity licenses."""

    snapshot_path: str = knob(
        "", "dictionary.snapshot-path",
        "global-dictionary snapshot location (filesystem SPI; empty = "
        "snapshots off): versioned code assignments persisted atomically "
        "so a restarted coordinator resolves codes before the first query",
    )
    max_inline_values: int = knob(
        1 << 16, "dictionary.max-inline-values",
        "largest dictionary whose values inline into snapshots/manifests; "
        "bigger (and pattern-backed) dictionaries snapshot as metadata "
        "only and re-adopt their recorded version at re-registration",
    )


@dataclass
class DispatcherConfig(ConfigSection):
    """Concurrent query dispatcher (runtime/dispatcher.QueryDispatcher):
    admission control, weighted-fair resource groups, load shedding."""

    lanes: int = knob(
        4, "dispatcher.lanes",
        "engine lanes (concurrent query executions) the dispatcher "
        "interleaves onto the device; runners that cannot be cloned "
        "(multi-host) are clamped to 1",
    )
    retry_after_s: float = knob(
        1.0, "dispatcher.retry-after",
        "Retry-After seconds a shed statement (HTTP 429: resource-group "
        "queue full) advertises to clients",
    )
    drain_wait_s: float = knob(
        30.0, "dispatcher.drain-wait",
        "seconds a dispatcher drain waits for running queries before "
        "force-killing them through their lifecycle tokens",
    )
    drain_grace_s: float = knob(
        5.0, "dispatcher.drain-grace",
        "seconds a drain waits AFTER force-kill for the canceled queries "
        "to reach their next cooperative check and release their lanes",
    )


@dataclass
class ProfileConfig(ConfigSection):
    """Query performance observatory: the persistent per-query profile
    archive (telemetry/profile_store.ProfileStore).  At completion every
    statement's profile — phases, per-fragment stats, collective bytes,
    compile events, admission info, gate wait, peak memory — is assembled
    into ONE structured artifact and persisted through the filesystem SPI
    off the hot path, so regressions can be *diffed* (tools/profile_diff)
    instead of re-measured from memory of last week's numbers."""

    archive_dir: str = knob(
        "", "profile.archive-dir",
        "profile-artifact archive location (filesystem SPI; empty = "
        "in-memory ring only when a store is attached, nothing otherwise)",
    )
    retention_max_age_s: float = knob(
        0.0, "profile.retention-max-age",
        "seconds an archived artifact is retained before the sweep "
        "deletes it (0 = keep forever)",
    )
    retention_max_count: int = knob(
        0, "profile.retention-max-count",
        "archived artifacts retained on disk, oldest pruned first "
        "(0 = unbounded)",
    )
    ring_limit: int = knob(
        256, "profile.ring-limit",
        "recent artifacts held in memory (the system.runtime."
        "query_profiles window; archived files are not bounded by this)",
    )


@dataclass
class AuditConfig(ConfigSection):
    """Structured JSONL query audit log (telemetry/audit.QueryAuditLog):
    one line per QueryCompletedEvent through the filesystem SPI, with
    size-based rotation — the machine-readable trail an external audit
    pipeline tails (reference role: http/kafka event listeners)."""

    log_path: str = knob(
        "", "audit.log-path",
        "audit log location (filesystem SPI; empty = audit log off)",
    )
    rotate_bytes: int = knob(
        64 * 1024 * 1024, "audit.rotate-bytes",
        "rotate the audit log when it would exceed this size "
        "(0 = never rotate)",
    )
    rotate_keep: int = knob(
        2, "audit.rotate-keep",
        "rotated audit segments kept (<path>.1 .. <path>.N, newest first)",
    )


@dataclass
class MemoryConfig(ConfigSection):
    """Shared-pool memory knobs (runtime/lifecycle LowMemoryKiller)."""

    pool_limit_bytes: int = knob(
        0, "memory.pool-limit-bytes",
        "shared device-memory pool limit arming the revoke -> kill "
        "escalation (0 = unlimited)",
    )
    spill_dir: str = knob(
        "", "memory.spill-dir",
        "directory for partition-wave spill files (filesystem SPI; "
        "empty = a per-process temp directory)",
    )


@dataclass
class ClusterConfig:
    """All subsystem sections plus the raw properties (kept for per-worker
    override resolution at breaker-creation time)."""

    breaker: BreakerConfig = field(default_factory=BreakerConfig)
    heartbeat: HeartbeatConfig = field(default_factory=HeartbeatConfig)
    lifecycle: LifecycleConfig = field(default_factory=LifecycleConfig)
    remote: RemoteConfig = field(default_factory=RemoteConfig)
    worker: WorkerConfig = field(default_factory=WorkerConfig)
    coordinator: CoordinatorConfig = field(default_factory=CoordinatorConfig)
    dispatcher: DispatcherConfig = field(default_factory=DispatcherConfig)
    memory: MemoryConfig = field(default_factory=MemoryConfig)
    compile_cache: CompileCacheConfig = field(
        default_factory=CompileCacheConfig
    )
    prewarm: PrewarmConfig = field(default_factory=PrewarmConfig)
    dictionary: DictionaryConfig = field(default_factory=DictionaryConfig)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    audit: AuditConfig = field(default_factory=AuditConfig)
    properties: dict = field(default_factory=dict)

    def breaker_for(self, worker: str) -> BreakerConfig:
        """Breaker knobs for ONE worker: base config plus any
        ``breaker.<knob>@<token>`` overrides matching its id."""
        return BreakerConfig.from_properties(
            self.properties, env=self._env, worker=worker
        )

    def section_for(self, section: str, worker: Optional[str] = None,
                    catalog: Optional[str] = None) -> ConfigSection:
        """Re-resolve one subsystem section ('breaker', 'worker', ...)
        scoped to a worker and/or catalog: ``<key>@<catalog>`` (exact
        catalog name, between env and the per-worker tier) and
        ``<key>@<token>`` overrides apply on top of the base config."""
        cls = type(getattr(self, section))
        return cls.from_properties(
            self.properties, env=self._env, worker=worker, catalog=catalog
        )

    #: env mapping captured at load so breaker_for stays reproducible
    _env = None


def load_cluster_config(props: Optional[dict] = None, env=None) -> ClusterConfig:
    """Build a ClusterConfig from a properties dict (e.g. the parsed
    ``etc/config.properties``) + environment overrides."""
    props = dict(props or {})
    env = os.environ if env is None else env
    cfg = ClusterConfig(
        breaker=BreakerConfig.from_properties(props, env),
        heartbeat=HeartbeatConfig.from_properties(props, env),
        lifecycle=LifecycleConfig.from_properties(props, env),
        remote=RemoteConfig.from_properties(props, env),
        worker=WorkerConfig.from_properties(props, env),
        coordinator=CoordinatorConfig.from_properties(props, env),
        dispatcher=DispatcherConfig.from_properties(props, env),
        memory=MemoryConfig.from_properties(props, env),
        compile_cache=CompileCacheConfig.from_properties(props, env),
        prewarm=PrewarmConfig.from_properties(props, env),
        dictionary=DictionaryConfig.from_properties(props, env),
        profile=ProfileConfig.from_properties(props, env),
        audit=AuditConfig.from_properties(props, env),
        properties=props,
    )
    cfg._env = env
    return cfg


def load_config(path: Optional[str] = None, props: Optional[dict] = None,
                env=None) -> ClusterConfig:
    """Load + install the process config from a .properties file path or a
    dict; returns the installed ClusterConfig."""
    if path is not None:
        from trino_tpu.runtime.config import load_properties

        props = load_properties(path)
    cfg = load_cluster_config(props, env)
    install_config(cfg)
    return cfg


# -- process-wide instance -----------------------------------------------------

_LOCK = threading.Lock()
_CURRENT = ClusterConfig()


def get_config() -> ClusterConfig:
    """The installed process configuration (defaults when none loaded)."""
    return _CURRENT


def install_config(cfg: ClusterConfig) -> None:
    global _CURRENT
    with _LOCK:
        _CURRENT = cfg
    # memory + compile-cache knobs take effect on install (the eager side
    # effects — everything else is read at use time).  The compile cache
    # must apply BEFORE the first jit, so install time — which load_etc
    # hits during server bring-up — is exactly right.
    if cfg.memory.pool_limit_bytes:
        from trino_tpu.runtime.lifecycle import set_memory_pool_limit

        set_memory_pool_limit(cfg.memory.pool_limit_bytes)
    if cfg.compile_cache.enabled and cfg.compile_cache.dir:
        from trino_tpu.runtime.prewarm import enable_persistent_compile_cache

        enable_persistent_compile_cache(cfg)
    else:
        # a reload that turns the cache OFF (enabled=false, or dir unset)
        # must actually detach it — the master switch is a switch, not a
        # one-way latch.  Only when a cache is live: a pure-config process
        # that never touched jax must not import it here.
        import sys as _sys

        spmd = _sys.modules.get("trino_tpu.parallel.spmd")
        if spmd is not None and spmd.PERSISTENT_CACHE_DIR:
            spmd.configure_persistent_cache(None)


def reset_config() -> None:
    """Restore compiled-in defaults (tests only)."""
    global _CURRENT
    with _LOCK:
        _CURRENT = ClusterConfig()
