"""Fault-tolerant execution: spooled stage outputs, task retry, heartbeats.

Reference: execution/scheduler/EventDrivenFaultTolerantQueryScheduler.java
(stage-by-stage execution with replayable intermediates),
core/trino-spi/.../spi/exchange/ExchangeManager.java:42 +
plugin/trino-exchange-filesystem (spooled exchange storage),
execution/DeduplicatingDirectExchangeBuffer (exactly-once consumption of
speculative/duplicate task attempts), and
failuredetector/HeartbeatFailureDetector.java:78 — the detector itself now
lives in runtime/membership (one implementation, sticky death, breaker
integration); the alias below keeps this module's import surface.

TPU mapping: a "task" is one fragment execution over the mesh; its output
(a stacked device batch or host batches) is the replayable unit.  The spool
persists fragment outputs host-side (npz files) keyed by
``(query_id, fragment_id, attempt_id)``, so a failed downstream fragment —
or a whole recovery pass after a worker death — retries WITHOUT re-running
its finished children (the EventDriven scheduler's core property).  Writes
are crash-atomic (a ``.tmp`` sibling renamed through the filesystem SPI):
a writer killed mid-save can never leave a torn ``.npz`` that a retrying
consumer would load.  Duplicate attempt outputs are deduplicated at the
CONSUMER: ``AttemptDedup`` commits exactly one attempt per fragment, and
every other attempt's output is discarded unread.
"""

from __future__ import annotations

import os
import re
import tempfile
import threading
import time
from typing import Callable, Optional

import numpy as np

# the ONE heartbeat failure detector (unified into runtime/membership —
# timeout facade over ClusterMembership with sticky death + breaker
# integration); re-exported here for the module's historical import surface
from trino_tpu.runtime.membership import (  # noqa: F401
    HeartbeatFailureDetector,
)

#: spool files older than this are orphans (their query is long gone — a
#: crashed coordinator never reaches SpoolManager.close); swept on
#: construction of any manager sharing the directory (reference:
#: FileSystemExchangeManager's exchange-directory cleanup on startup)
SPOOL_ORPHAN_MAX_AGE_S = 6 * 3600.0

#: committed spool filename shape: {query_id}_f{fid}.npz for attempt 0
#: (the historical name, shared with the spill tier) and
#: {query_id}_f{fid}_a{attempt}.npz for retry attempts
_ATTEMPT_RE = re.compile(r"_f(\d+)(?:_a(\d+))?\.npz$")


class AttemptDedup:
    """Consumer-side exactly-once attempt selection (reference:
    DeduplicatingDirectExchangeBuffer): speculative or duplicate task
    attempts may each spool an output for the same ``(query_id,
    fragment_id)``; the FIRST attempt a consumer commits wins, every
    consumer thereafter reads that same attempt, and the duplicates are
    discarded unread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._committed: dict[tuple, int] = {}

    def commit(self, query_id: str, fragment_id: int, attempt_id: int) -> int:
        """Commit an attempt for consumption; returns the attempt EVERY
        consumer must read (the first committed one — a later speculative
        attempt's commit is a no-op and is told which attempt won)."""
        key = (query_id, int(fragment_id))
        with self._lock:
            return self._committed.setdefault(key, int(attempt_id))

    def committed(self, query_id: str, fragment_id: int) -> Optional[int]:
        with self._lock:
            return self._committed.get((query_id, int(fragment_id)))

    def clear(self, query_id: str) -> None:
        with self._lock:
            for key in [k for k in self._committed if k[0] == query_id]:
                del self._committed[key]


class SpoolManager:
    """Persist per-fragment outputs to local files (reference role:
    FileSystemExchangeManager / LocalFileSystemExchangeStorage)."""

    def __init__(
        self,
        directory: Optional[str] = None,
        orphan_max_age_s: float = SPOOL_ORPHAN_MAX_AGE_S,
        clock: Callable[[], float] = time.time,
    ):
        from trino_tpu.filesystem import filesystem_for, strip_scheme

        self._own = directory is None
        self.clock = clock
        # the filesystem SPI resolves the location (and rejects remote
        # schemes loudly until an object-store implementation lands)
        self.fs = filesystem_for(directory)
        self.dir = strip_scheme(
            directory or tempfile.mkdtemp(prefix="trino_tpu_spool_")
        )
        self.fs.mkdirs(self.dir)
        #: exactly-once attempt selection for this spool's consumers
        self.dedup = AttemptDedup()
        if not self._own:
            # a SHARED directory accumulates {qid}_f{fid}[_a{n}].npz
            # orphans from queries that crashed before close(); sweep them
            # by age so the spool volume is bounded by live work, not by
            # failure history
            self.gc(orphan_max_age_s)

    def _path(
        self, query_id: str, fragment_id: int, attempt_id: int = 0
    ) -> str:
        suffix = f"_a{attempt_id}" if attempt_id else ""
        return os.path.join(
            self.dir, f"{query_id}_f{fragment_id}{suffix}.npz"
        )

    def save(self, query_id: str, fragment_id: int, batches, symbols,
             attempt_id: int = 0) -> str:
        """Spool host batches (list of Batch) for one fragment attempt.

        CRASH-ATOMIC: the npz streams into a ``.tmp`` sibling and is
        renamed into place through the filesystem SPI (one atomic
        ``os.replace`` on the local implementation) — a writer killed
        mid-save leaves at worst a ``.tmp`` the GC sweeps, never a torn
        ``.npz`` a retrying consumer would load."""
        arrays: dict = {"__nbatches__": np.asarray(len(batches))}
        for bi, b in enumerate(batches):
            arrays[f"b{bi}_mask"] = np.asarray(b.mask())
            for ci, c in enumerate(b.columns):
                arrays[f"b{bi}_c{ci}_data"] = np.asarray(c.data)
                if c.valid is not None:
                    arrays[f"b{bi}_c{ci}_valid"] = np.asarray(c.valid)
                if c.lengths is not None:
                    # array columns: per-row element counts ride along so a
                    # spilled/spooled batch rehydrates exactly
                    arrays[f"b{bi}_c{ci}_len"] = np.asarray(c.lengths)
        path = self._path(query_id, fragment_id, attempt_id)
        tmp = path + ".tmp"
        try:
            with self.fs.open_output(tmp) as f:  # streaming: no double-buffer
                np.savez(f, **arrays)
        except BaseException:
            # a failed/killed write must not leave the torn sibling behind
            # for the next writer to trip on
            try:
                self.fs.delete(tmp)
            except OSError:
                pass
            raise
        self.fs.rename(tmp, path)
        return path

    def load(self, query_id: str, fragment_id: int, symbols, dictionaries,
             attempt_id: int = 0):
        """Rehydrate spooled batches (schema from the fragment's symbols).

        `dictionaries` is validated against the stored codes instead of
        taken on faith: a stale or mis-keyed dictionary list would decode
        spooled codes into the WRONG strings silently — a clear error at
        load beats corrupt results downstream."""
        from trino_tpu.columnar import Batch, Column

        path = self._path(query_id, fragment_id, attempt_id)
        if not self.fs.exists(path):
            return None
        if len(dictionaries) != len(symbols):
            raise ValueError(
                f"spool load {query_id}/f{fragment_id}: {len(dictionaries)} "
                f"dictionaries for {len(symbols)} columns"
            )
        z = np.load(self.fs.open_input(path), allow_pickle=False)
        out = []
        for bi in range(int(z["__nbatches__"])):
            cols = []
            mask = z[f"b{bi}_mask"]
            for ci, sym in enumerate(symbols):
                data = z[f"b{bi}_c{ci}_data"]
                valid = z.get(f"b{bi}_c{ci}_valid")
                d = dictionaries[ci]
                if d is not None and data.size:
                    live = mask.astype(bool)
                    if valid is not None:
                        live = live & valid.astype(bool)
                    codes = data[live] if live.any() else data[:0]
                    if codes.size and int(codes.max()) >= len(d):
                        raise ValueError(
                            f"spool load {query_id}/f{fragment_id} column "
                            f"{sym.name}: stored code {int(codes.max())} out "
                            f"of range for dictionary of {len(d)} values — "
                            "the dictionary list does not match the spooled "
                            "batches"
                        )
                cols.append(
                    Column(data, sym.type, valid, d,
                           z.get(f"b{bi}_c{ci}_len"))
                )
            out.append(Batch(cols, mask))
        return out

    def exists(self, query_id: str, fragment_id: int,
               attempt_id: int = 0) -> bool:
        return self.fs.exists(self._path(query_id, fragment_id, attempt_id))

    def attempts(self, query_id: str, fragment_id: int) -> list:
        """Committed (fully renamed) attempt ids spooled for a fragment,
        ascending.  ``.tmp`` siblings are invisible by construction — an
        attempt only appears here after its atomic rename."""
        prefix = f"{query_id}_f"
        out = []
        for p in list(self.fs.list(self.dir)):
            name = os.path.basename(p)
            if not name.startswith(prefix):
                continue
            m = _ATTEMPT_RE.search(name)
            if m is None or int(m.group(1)) != int(fragment_id):
                continue
            out.append(int(m.group(2) or 0))
        return sorted(out)

    def discard_duplicates(self, query_id: str, fragment_id: int,
                           keep_attempt: int) -> int:
        """Delete every spooled attempt EXCEPT the committed one (the
        DeduplicatingDirectExchangeBuffer discard: duplicate/speculative
        outputs must never be consumed, and holding them costs spool
        volume).  Returns the number of duplicates removed."""
        removed = 0
        for att in self.attempts(query_id, fragment_id):
            if att == keep_attempt:
                continue
            try:
                self.fs.delete(self._path(query_id, fragment_id, att))
                removed += 1
            except OSError:
                continue
        return removed

    def gc(self, max_age_s: float) -> list:
        """Delete spool files not modified within `max_age_s` seconds;
        returns the paths removed.  Age-based (not liveness-based) on
        purpose: the writer may be a coordinator in another process, so
        mtime is the only signal every deployment shape shares.  Torn
        ``.npz.tmp`` siblings (a writer killed mid-save) age out the same
        way.  All IO (list/mtime/delete) rides the filesystem SPI, so GC
        follows the spool to whatever storage implementation hosts it."""
        cutoff = self.clock() - max_age_s
        removed = []
        for p in list(self.fs.list(self.dir)):
            if not (p.endswith(".npz") or p.endswith(".npz.tmp")):
                continue  # never touch files the spool didn't write
            try:
                if self.fs.mtime(p) < cutoff:
                    self.fs.delete(p)
                    removed.append(p)
            except OSError:
                continue  # deleted concurrently (another manager's sweep)
        return removed

    def close(self) -> None:
        """Remove spooled intermediates (query finished); only directories
        this manager created are deleted.  Everything routes through the
        filesystem SPI — including the directory removal — so cleanup
        follows object-store spool implementations when they land."""
        if self._own:
            for p in list(self.fs.list(self.dir)):
                self.fs.delete(p)
            self.fs.delete_recursive(self.dir)
