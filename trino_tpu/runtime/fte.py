"""Fault-tolerant execution: spooled stage outputs, task retry, heartbeats.

Reference: execution/scheduler/EventDrivenFaultTolerantQueryScheduler.java
(stage-by-stage execution with replayable intermediates),
core/trino-spi/.../spi/exchange/ExchangeManager.java:42 +
plugin/trino-exchange-filesystem (spooled exchange storage),
failuredetector/HeartbeatFailureDetector.java:78.

TPU mapping: a "task" is one fragment execution over the mesh; its output
(a stacked device batch or host batches) is the replayable unit.  The spool
persists fragment outputs host-side (npz files), so a failed downstream
fragment retries WITHOUT re-running its finished children — the
EventDriven scheduler's core property.  The heartbeat detector watches
worker liveness the coordinator-side way; with in-process mesh workers it
guards the host feeder threads and remote (server-mode) workers.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Callable, Optional

import numpy as np


#: spool files older than this are orphans (their query is long gone — a
#: crashed coordinator never reaches SpoolManager.close); swept on
#: construction of any manager sharing the directory (reference:
#: FileSystemExchangeManager's exchange-directory cleanup on startup)
SPOOL_ORPHAN_MAX_AGE_S = 6 * 3600.0


class SpoolManager:
    """Persist per-fragment outputs to local files (reference role:
    FileSystemExchangeManager / LocalFileSystemExchangeStorage)."""

    def __init__(
        self,
        directory: Optional[str] = None,
        orphan_max_age_s: float = SPOOL_ORPHAN_MAX_AGE_S,
        clock: Callable[[], float] = time.time,
    ):
        from trino_tpu.filesystem import filesystem_for, strip_scheme

        self._own = directory is None
        self.clock = clock
        # the filesystem SPI resolves the location (and rejects remote
        # schemes loudly until an object-store implementation lands)
        self.fs = filesystem_for(directory)
        self.dir = strip_scheme(
            directory or tempfile.mkdtemp(prefix="trino_tpu_spool_")
        )
        self.fs.mkdirs(self.dir)
        if not self._own:
            # a SHARED directory accumulates {qid}_f{fid}.npz orphans from
            # queries that crashed before close(); sweep them by age so the
            # spool volume is bounded by live work, not by failure history
            self.gc(orphan_max_age_s)

    def _path(self, query_id: str, fragment_id: int) -> str:
        return os.path.join(self.dir, f"{query_id}_f{fragment_id}.npz")

    def save(self, query_id: str, fragment_id: int, batches, symbols) -> str:
        """Spool host batches (list of Batch) for one fragment."""
        arrays: dict = {"__nbatches__": np.asarray(len(batches))}
        for bi, b in enumerate(batches):
            arrays[f"b{bi}_mask"] = np.asarray(b.mask())
            for ci, c in enumerate(b.columns):
                arrays[f"b{bi}_c{ci}_data"] = np.asarray(c.data)
                if c.valid is not None:
                    arrays[f"b{bi}_c{ci}_valid"] = np.asarray(c.valid)
                if c.lengths is not None:
                    # array columns: per-row element counts ride along so a
                    # spilled/spooled batch rehydrates exactly
                    arrays[f"b{bi}_c{ci}_len"] = np.asarray(c.lengths)
        path = self._path(query_id, fragment_id)
        with self.fs.open_output(path) as f:  # streaming: no double-buffer
            np.savez(f, **arrays)
        return path

    def load(self, query_id: str, fragment_id: int, symbols, dictionaries):
        """Rehydrate spooled batches (schema from the fragment's symbols).

        `dictionaries` is validated against the stored codes instead of
        taken on faith: a stale or mis-keyed dictionary list would decode
        spooled codes into the WRONG strings silently — a clear error at
        load beats corrupt results downstream."""
        from trino_tpu.columnar import Batch, Column

        path = self._path(query_id, fragment_id)
        if not self.fs.exists(path):
            return None
        if len(dictionaries) != len(symbols):
            raise ValueError(
                f"spool load {query_id}/f{fragment_id}: {len(dictionaries)} "
                f"dictionaries for {len(symbols)} columns"
            )
        z = np.load(self.fs.open_input(path), allow_pickle=False)
        out = []
        for bi in range(int(z["__nbatches__"])):
            cols = []
            mask = z[f"b{bi}_mask"]
            for ci, sym in enumerate(symbols):
                data = z[f"b{bi}_c{ci}_data"]
                valid = z.get(f"b{bi}_c{ci}_valid")
                d = dictionaries[ci]
                if d is not None and data.size:
                    live = mask.astype(bool)
                    if valid is not None:
                        live = live & valid.astype(bool)
                    codes = data[live] if live.any() else data[:0]
                    if codes.size and int(codes.max()) >= len(d):
                        raise ValueError(
                            f"spool load {query_id}/f{fragment_id} column "
                            f"{sym.name}: stored code {int(codes.max())} out "
                            f"of range for dictionary of {len(d)} values — "
                            "the dictionary list does not match the spooled "
                            "batches"
                        )
                cols.append(
                    Column(data, sym.type, valid, d,
                           z.get(f"b{bi}_c{ci}_len"))
                )
            out.append(Batch(cols, mask))
        return out

    def exists(self, query_id: str, fragment_id: int) -> bool:
        return self.fs.exists(self._path(query_id, fragment_id))

    def gc(self, max_age_s: float) -> list:
        """Delete spool files not modified within `max_age_s` seconds;
        returns the paths removed.  Age-based (not liveness-based) on
        purpose: the writer may be a coordinator in another process, so
        mtime is the only signal every deployment shape shares.  All IO
        (list/mtime/delete) rides the filesystem SPI, so GC follows the
        spool to whatever storage implementation hosts it."""
        cutoff = self.clock() - max_age_s
        removed = []
        for p in list(self.fs.list(self.dir)):
            if not p.endswith(".npz"):
                continue  # never touch files the spool didn't write
            try:
                if self.fs.mtime(p) < cutoff:
                    self.fs.delete(p)
                    removed.append(p)
            except OSError:
                continue  # deleted concurrently (another manager's sweep)
        return removed

    def close(self) -> None:
        """Remove spooled intermediates (query finished); only directories
        this manager created are deleted.  Everything routes through the
        filesystem SPI — including the directory removal — so cleanup
        follows object-store spool implementations when they land."""
        if self._own:
            for p in list(self.fs.list(self.dir)):
                self.fs.delete(p)
            self.fs.delete_recursive(self.dir)


class HeartbeatFailureDetector:
    """Coordinator-side liveness tracking (reference:
    failuredetector/HeartbeatFailureDetector.java:78, ping():350): workers
    heartbeat; ones silent past the threshold are marked failed and excluded
    from scheduling."""

    def __init__(self, timeout_s: float = 10.0, clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self.clock = clock
        self._last: dict[str, float] = {}
        self._failed: set[str] = set()

    def register(self, worker: str) -> None:
        self._last[worker] = self.clock()
        self._failed.discard(worker)

    def unregister(self, worker: str) -> None:
        """Forget a worker entirely (a mesh SHRINK removes it by intent —
        the stale entry must not time out and fail liveness checks that no
        longer concern it)."""
        self._last.pop(worker, None)
        self._failed.discard(worker)

    def heartbeat(self, worker: str) -> None:
        self._last[worker] = self.clock()
        self._failed.discard(worker)

    def refresh(self) -> None:
        now = self.clock()
        # snapshot: concurrent heartbeat()/register() calls resize the dict
        # mid-iteration (RuntimeError under load).  dict.copy() is one
        # atomic C-level operation under the GIL; list(items()) is NOT —
        # its iteration can still observe the resize
        for w, t in self._last.copy().items():
            if now - t > self.timeout_s:
                self._failed.add(w)

    def failed_workers(self) -> set:
        self.refresh()
        return set(self._failed)

    def active_workers(self) -> list:
        self.refresh()
        return sorted(w for w in self._last if w not in self._failed)

    def is_alive(self, worker: str) -> bool:
        self.refresh()
        return worker in self._last and worker not in self._failed
