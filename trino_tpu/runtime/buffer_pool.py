"""Scan buffer pool: two-tier (host RAM / device HBM) cache of scan batches.

Reference roles: the OS page cache + connector-level caching that keeps a warm
Java Trino from re-reading ORC bytes per query, and `MemoryPagesStore`'s role
of serving hot tables from RAM.  On a TPU the analogous scarce path is
host→device transfer (PCIe or, under the axon tunnel, a remote link measured
in tens of MB/s), so the pool keeps *device-resident* batches for repeated
scans of immutable splits — a buffer pool over HBM — with a host tier of
already-padded numpy batches below it.

Entries are keyed by (table, split slice, projected columns, page size,
connector scan version); a connector that cannot guarantee immutability
returns version None and is never cached.  Both tiers are byte-budgeted LRU,
accounted through runtime/memory.py MemoryContext so budgets are visible in
the same reservation tree the operators use.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

from trino_tpu.runtime.memory import MemoryContext, batch_bytes


def _env_bytes(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class _Tier:
    """One byte-budgeted LRU tier."""

    def __init__(self, name: str, limit_bytes: int):
        self.name = name
        self.limit_bytes = limit_bytes
        self.entries: OrderedDict = OrderedDict()  # key -> (batches, nbytes)
        self.ctx = MemoryContext(None, f"buffer_pool:{name}")
        self.hits = 0
        self.misses = 0

    def get(self, key):
        e = self.entries.get(key)
        if e is None:
            self.misses += 1
            return None
        self.entries.move_to_end(key)
        self.hits += 1
        return e[0]

    def put(self, key, batches, nbytes: int) -> None:
        if nbytes > self.limit_bytes:
            return  # larger than the whole tier: don't thrash
        old = self.entries.pop(key, None)
        if old is not None:
            self.ctx.add_bytes(-old[1])
        while self.entries and self.ctx.reserved + nbytes > self.limit_bytes:
            _, (_, old_bytes) = self.entries.popitem(last=False)
            self.ctx.add_bytes(-old_bytes)
        self.entries[key] = (batches, nbytes)
        self.ctx.add_bytes(nbytes)

    def clear(self) -> None:
        self.entries.clear()
        self.ctx.set_bytes(0)


class BufferPool:
    def __init__(
        self,
        host_limit_bytes: Optional[int] = None,
        device_limit_bytes: Optional[int] = None,
    ):
        if host_limit_bytes is None:
            host_limit_bytes = _env_bytes(
                "TRINO_TPU_HOST_CACHE_BYTES", 6 << 30
            )
        if device_limit_bytes is None:
            device_limit_bytes = _env_bytes(
                "TRINO_TPU_DEVICE_CACHE_BYTES", 8 << 30
            )
        self.host = _Tier("host", host_limit_bytes)
        self.device = _Tier("device", device_limit_bytes)
        self.lock = threading.Lock()

    @staticmethod
    def split_key(split, columns, page_rows: int, version) -> tuple:
        t = split.table
        return (
            t.catalog,
            t.schema,
            t.table,
            split.seq,
            split.row_start,
            split.row_count,
            tuple(columns),
            page_rows,
            version,
        )

    def get_device(self, key):
        with self.lock:
            return self.device.get(key)

    def put_device(self, key, batches) -> None:
        nbytes = sum(batch_bytes(b) for b in batches)
        with self.lock:
            self.device.put(key, list(batches), nbytes)

    def get_host(self, key):
        with self.lock:
            return self.host.get(key)

    def put_host(self, key, batches) -> None:
        nbytes = sum(batch_bytes(b) for b in batches)
        with self.lock:
            self.host.put(key, list(batches), nbytes)

    def invalidate_device(self, stale) -> int:
        """Drop device-tier entries whose key satisfies `stale(key)`;
        returns how many were dropped.  Used by membership's mesh-shrink
        re-planning to evict stacked-scan batches keyed by a mesh signature
        that no longer exists (runtime/membership.invalidate_mesh_scans)."""
        dropped = 0
        with self.lock:
            for key in [k for k in self.device.entries if stale(k)]:
                _, nbytes = self.device.entries.pop(key)
                self.device.ctx.add_bytes(-nbytes)
                dropped += 1
        return dropped

    def clear(self) -> None:
        with self.lock:
            self.host.clear()
            self.device.clear()

    def stats(self) -> dict:
        with self.lock:
            return {
                "host_bytes": self.host.ctx.reserved,
                "host_hits": self.host.hits,
                "host_misses": self.host.misses,
                "device_bytes": self.device.ctx.reserved,
                "device_hits": self.device.hits,
                "device_misses": self.device.misses,
            }


#: process-wide pool (the engine is one process per host, like a worker JVM)
POOL = BufferPool()
