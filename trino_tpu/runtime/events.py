"""Query event pipeline (reference: event/QueryMonitor.java ->
eventlistener/EventListenerManager.java -> spi eventlistener plugins).

Listeners receive QueryCreatedEvent / QueryCompletedEvent; failures carry the
error string plus an error TYPE classification (USER_ERROR | INTERNAL_ERROR;
reference role: spi ErrorCode/ErrorType), and completions carry a
QueryStatistics payload (wall, phase totals, counters, peak memory — what
EXPLAIN ANALYZE sees, reference: spi eventlistener QueryStatistics).  The
bundled FileEventListener mirrors trino-http-event-listener's role as the
simplest sink.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("trino_tpu.events")

#: error-type vocabulary (reference: spi ErrorType — the subset the engine
#: distinguishes; external classes fold into INTERNAL here)
USER_ERROR = "USER_ERROR"
INTERNAL_ERROR = "INTERNAL_ERROR"
#: deadline / memory-kill / admission aborts (reference: INSUFFICIENT_
#: RESOURCES — the class a serving stack pages on differently from bugs)
RESOURCE_ERROR = "RESOURCE_ERROR"


def classify_error(exc: BaseException) -> str:
    """Exception -> error type.  Lifecycle aborts classify first (a user
    cancel is the user's, a deadline/memory kill is a resource verdict —
    both are RuntimeErrors, so they must not fall through to INTERNAL).
    Parse/analysis/semantic errors (the engine raises them as ValueError
    subclasses — ParseError, AnalysisError — plus KeyError for missing
    objects and NotImplementedError for unsupported SQL) are the user's;
    everything else is the engine's."""
    from trino_tpu.runtime.lifecycle import (
        QueryAbortedException,
        QueryCanceledException,
    )
    from trino_tpu.runtime.memory import ExceededMemoryLimitException

    if isinstance(exc, QueryCanceledException):
        return USER_ERROR
    if isinstance(exc, (QueryAbortedException, ExceededMemoryLimitException)):
        return RESOURCE_ERROR
    if isinstance(exc, (ValueError, KeyError, NotImplementedError)):
        return USER_ERROR
    return INTERNAL_ERROR


@dataclass
class QueryStatistics:
    """Per-query execution statistics delivered with QueryCompletedEvent
    (reference: spi eventlistener QueryStatistics — listeners see what
    EXPLAIN ANALYZE sees, machine-readable)."""

    wall_s: float = 0.0
    rows: int = 0
    #: per-phase seconds summed over distributed fragments (empty for
    #: purely local executions)
    phase_totals_s: dict = field(default_factory=dict)
    #: MeshProfile counters of the execution (empty when local)
    counters: dict = field(default_factory=dict)
    #: trace-cache hits/misses/retraces attributed to this query
    trace_cache: dict = field(default_factory=dict)
    peak_memory_bytes: int = 0
    #: spans recorded by the query tracer (0 when tracing is off)
    spans: int = 0
    #: seconds the statement waited on the device time-slice gate
    #: (runtime/dispatcher device_slice; contended acquires only)
    gate_wait_s: float = 0.0
    #: resource group the statement was admitted through + its queue wait
    #: (empty/0 for undispatched executions)
    group: str = ""
    queued_s: float = 0.0
    #: archived profile-artifact key (telemetry/profile_store; empty when
    #: no store is attached)
    profile_key: str = ""


@dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float


@dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED | CANCELED
    create_time: float
    end_time: float
    rows: int = 0
    error: Optional[str] = None
    #: USER_ERROR | INTERNAL_ERROR | RESOURCE_ERROR when not FINISHED
    error_type: Optional[str] = None
    #: lifecycle kill reason when the query was aborted (USER_CANCELED |
    #: EXCEEDED_TIME_LIMIT | CLUSTER_OUT_OF_MEMORY; reference: ErrorCode
    #: name) — the `system.runtime.queries` kill-reason column
    error_code: Optional[str] = None
    statistics: Optional[QueryStatistics] = None

    @property
    def wall_s(self) -> float:
        return self.end_time - self.create_time


class EventListener:
    def query_created(self, event: QueryCreatedEvent) -> None:  # pragma: no cover
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # pragma: no cover
        pass


class EventListenerManager:
    def __init__(self):
        self.listeners: list[EventListener] = []
        #: (listener class name, event kind) pairs already warned about —
        #: a broken audit sink logs ONE rate-limited warning per listener
        #: class per event type instead of failing silently forever
        self._warned: set = set()

    def add(self, listener: EventListener) -> None:
        self.listeners.append(listener)

    def _deliver(self, method: str, event) -> None:
        for l in self.listeners:
            try:
                getattr(l, method)(event)
            except Exception:
                # listeners must not break queries, but a dead sink must be
                # VISIBLE: warn once per (listener class, event type)
                key = (type(l).__name__, method)
                if key not in self._warned:
                    self._warned.add(key)
                    log.warning(
                        "event listener %s failed handling %s (suppressing "
                        "further warnings for this listener/event pair)",
                        type(l).__name__,
                        method,
                        exc_info=True,
                    )

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._deliver("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._deliver("query_completed", event)


class FileEventListener(EventListener):
    """Append query events as JSON lines (reference role: the
    http/kafka event-listener plugins' sink, file-backed — the shape an
    external audit pipeline ingests)."""

    def __init__(self, path: str):
        self.path = path
        # surface unwritable paths at STARTUP — the manager swallows
        # per-event listener errors, so a bad path would otherwise drop the
        # whole audit trail silently
        with open(path, "a", encoding="utf-8"):
            pass

    def _write(self, doc: dict) -> None:
        import json

        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(doc) + "\n")

    def query_created(self, e: QueryCreatedEvent) -> None:
        self._write(
            {
                "event": "query_created",
                "query_id": e.query_id,
                "sql": e.sql,
                "create_time": e.create_time,
            }
        )

    def query_completed(self, e: QueryCompletedEvent) -> None:
        self._write(
            {
                "event": "query_completed",
                "query_id": e.query_id,
                "state": e.state,
                "wall_s": e.wall_s,
                "rows": e.rows,
                "error": e.error,
                "error_type": e.error_type,
                "error_code": e.error_code,
            }
        )


class CollectingEventListener(EventListener):
    """Test fixture (reference: testing EventsCollector)."""

    def __init__(self):
        self.created: list[QueryCreatedEvent] = []
        self.completed: list[QueryCompletedEvent] = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)
