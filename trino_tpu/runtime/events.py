"""Query event pipeline (reference: event/QueryMonitor.java ->
eventlistener/EventListenerManager.java -> spi eventlistener plugins).

Listeners receive QueryCreatedEvent / QueryCompletedEvent; failures carry the
error.  The bundled LoggingEventListener mirrors trino-http-event-listener's
role as the simplest sink.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float


@dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED
    create_time: float
    end_time: float
    rows: int = 0
    error: Optional[str] = None

    @property
    def wall_s(self) -> float:
        return self.end_time - self.create_time


class EventListener:
    def query_created(self, event: QueryCreatedEvent) -> None:  # pragma: no cover
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # pragma: no cover
        pass


class EventListenerManager:
    def __init__(self):
        self.listeners: list[EventListener] = []

    def add(self, listener: EventListener) -> None:
        self.listeners.append(listener)

    def query_created(self, event: QueryCreatedEvent) -> None:
        for l in self.listeners:
            try:
                l.query_created(event)
            except Exception:
                pass  # listeners must not break queries

    def query_completed(self, event: QueryCompletedEvent) -> None:
        for l in self.listeners:
            try:
                l.query_completed(event)
            except Exception:
                pass


class FileEventListener(EventListener):
    """Append query events as JSON lines (reference role: the
    http/kafka event-listener plugins' sink, file-backed — the shape an
    external audit pipeline ingests)."""

    def __init__(self, path: str):
        self.path = path
        # surface unwritable paths at STARTUP — the manager swallows
        # per-event listener errors, so a bad path would otherwise drop the
        # whole audit trail silently
        with open(path, "a", encoding="utf-8"):
            pass

    def _write(self, doc: dict) -> None:
        import json

        with open(self.path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(doc) + "\n")

    def query_created(self, e: QueryCreatedEvent) -> None:
        self._write(
            {
                "event": "query_created",
                "query_id": e.query_id,
                "sql": e.sql,
                "create_time": e.create_time,
            }
        )

    def query_completed(self, e: QueryCompletedEvent) -> None:
        self._write(
            {
                "event": "query_completed",
                "query_id": e.query_id,
                "state": e.state,
                "wall_s": e.wall_s,
                "rows": e.rows,
                "error": e.error,
            }
        )


class CollectingEventListener(EventListener):
    """Test fixture (reference: testing EventsCollector)."""

    def __init__(self):
        self.created: list[QueryCreatedEvent] = []
        self.completed: list[QueryCompletedEvent] = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)
