"""Failure injection + query retry.

Reference: execution/FailureInjector.java:62,125 (injected task failures for
fault-tolerance tests) and RetryPolicy (operator/RetryPolicy.java) — NONE
(fail the query) vs QUERY (transparent re-execution).  Task-level retry with
spooled intermediates (the Tardigrade scheduler) follows once stages persist
their outputs; the injection/classification machinery here is shared.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Optional


class InjectedFailure(RuntimeError):
    """Retryable injected fault (reference: TASK_FAILURE injection type)."""


class StageFailedException(RuntimeError):
    """A stage exhausted its task-retry budget.  Deliberately NOT retryable:
    consuming stages must propagate it instead of burning their own budgets
    (task budgets are per-task, not multiplicative — the reference fails the
    query when any task exceeds task_retry_attempts_per_task)."""


@dataclass
class _Injection:
    match: str  # substring of the injection point name
    error: type
    remaining: int  # fire this many times, then stop


class FailureInjector:
    """Named injection points call `maybe_fail(point)`; tests arm failures."""

    def __init__(self):
        self._injections: list[_Injection] = []
        #: visit counter per injection point (lets fault-tolerance tests
        #: assert which stages re-ran and which were served from the spool)
        self.visits: dict[str, int] = {}

    def inject(self, match: str, times: int = 1, error: type = InjectedFailure):
        self._injections.append(_Injection(match, error, times))

    def maybe_fail(self, point: str) -> None:
        self.visits[point] = self.visits.get(point, 0) + 1
        for inj in self._injections:
            if inj.remaining > 0 and inj.match in point:
                inj.remaining -= 1
                raise inj.error(f"injected failure at {point}")

    def clear(self) -> None:
        self._injections.clear()
        self.visits.clear()


#: process-wide injector consulted by execution hooks (tests arm it)
FAILURE_INJECTOR = FailureInjector()

RETRYABLE = (InjectedFailure, ConnectionError, TimeoutError)


def execute_with_retry(fn, retry_policy: str = "NONE", max_attempts: int = 4):
    """Run fn() under the given retry policy (reference:
    SqlQueryExecution's retry handling for retry_policy=QUERY).  TASK-level
    retry happens inside the stage executor (parallel/runner.py); at this
    outer level it degrades to a final QUERY-style safety net."""
    if retry_policy == "NONE":
        return fn()
    assert retry_policy in ("QUERY", "TASK"), retry_policy
    if retry_policy == "TASK":
        # stage-level retry happens inside the stage executor; no outer
        # whole-query retries on top (reference: RetryPolicy.TASK)
        return fn()
    last: Optional[BaseException] = None
    for _ in range(max_attempts):
        try:
            return fn()
        except RETRYABLE as e:
            last = e
    raise last
