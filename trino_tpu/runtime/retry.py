"""Failure injection, backoff, circuit breakers, and query retry.

Reference: execution/FailureInjector.java:62,125 (injected task failures for
fault-tolerance tests), RetryPolicy (operator/RetryPolicy.java) — NONE
(fail the query) vs QUERY (transparent re-execution), Backoff.java (the
capped exponential wait every remote-task poll sits behind), and the
failure-detection side of HttpRemoteTask: a worker that keeps failing stops
receiving traffic until a probe succeeds (circuit breaking — the reference
spreads this across backoff + the failure detector; here it is explicit).

Everything time-related is injectable (clock / sleep / rng) so chaos tests
run on a deterministic clock without real sleeps.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional


class InjectedFailure(RuntimeError):
    """Retryable injected fault (reference: TASK_FAILURE injection type)."""


class StageFailedException(RuntimeError):
    """A stage exhausted its task-retry budget.  Deliberately NOT retryable:
    consuming stages must propagate it instead of burning their own budgets
    (task budgets are per-task, not multiplicative — the reference fails the
    query when any task exceeds task_retry_attempts_per_task)."""


@dataclass
class _Injection:
    match: str  # substring of the injection point name
    error: Optional[type]  # None = latency injection (sleep, don't raise)
    remaining: int  # fire this many times, then stop
    delay_s: float = 0.0


class FailureInjector:
    """Named injection points call `maybe_fail(point)`; tests arm failures.

    Modes (reference: FailureInjector's TASK_FAILURE / TASK_TIMEOUT types):
      inject(...)                  — raise an error at the point
      inject_latency(...)          — stall the point (timeout/deadline chaos)
      inject_connection_flap(...)  — raise ConnectionResetError (the flaky-
                                     network shape retries must absorb)
    """

    def __init__(self, sleep: Callable[[float], None] = time.sleep):
        self._injections: list[_Injection] = []
        #: visit counter per injection point (lets fault-tolerance tests
        #: assert which stages re-ran and which were served from the spool)
        self.visits: dict[str, int] = {}
        #: injectable so latency tests don't really sleep; clear() restores
        #: THIS default (tests may also set .sleep directly per-case)
        self._default_sleep = sleep
        self.sleep = sleep

    def inject(self, match: str, times: int = 1, error: type = InjectedFailure):
        self._injections.append(_Injection(match, error, times))

    def inject_latency(self, match: str, delay_s: float, times: int = 1):
        """Stall matching points by delay_s (latency-spike chaos)."""
        self._injections.append(_Injection(match, None, times, delay_s))

    def inject_connection_flap(self, match: str, times: int = 1):
        """Drop matching connections (retryable ConnectionResetError)."""
        self._injections.append(_Injection(match, ConnectionResetError, times))

    def maybe_fail(self, point: str) -> None:
        self.visits[point] = self.visits.get(point, 0) + 1
        for inj in self._injections:
            if inj.remaining > 0 and inj.match in point:
                inj.remaining -= 1
                if inj.error is None:
                    self.sleep(inj.delay_s)
                    continue
                raise inj.error(f"injected failure at {point}")

    def clear(self) -> None:
        self._injections.clear()
        self.visits.clear()
        self.sleep = self._default_sleep


#: process-wide injector consulted by execution hooks (tests arm it)
FAILURE_INJECTOR = FailureInjector()

RETRYABLE = (InjectedFailure, ConnectionError, TimeoutError)


class Backoff:
    """Capped exponential backoff with FULL jitter (reference: Backoff.java;
    jitter per the AWS architecture-blog analysis — full jitter desynchronizes
    retry storms better than equal jitter).  attempt 0 waits in
    [0, base), attempt k in [0, min(cap, base * 2**k))."""

    def __init__(
        self,
        base_s: float = 0.05,
        cap_s: float = 5.0,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if base_s <= 0:
            raise ValueError(f"backoff base must be positive: {base_s}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.rng = rng or random.Random()
        self._sleep = sleep
        #: total seconds slept (test/telemetry evidence)
        self.total_wait_s = 0.0

    def delay(self, attempt: int) -> float:
        """The jittered wait before retry number `attempt` (0-based)."""
        ceiling = min(self.cap_s, self.base_s * (2 ** max(0, attempt)))
        return self.rng.uniform(0.0, ceiling)

    def wait(self, attempt: int) -> float:
        d = self.delay(attempt)
        if d > 0:
            self._sleep(d)
        self.total_wait_s += d
        return d


# -- per-worker circuit breakers ----------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

#: numeric encoding for the metrics gauge (system.runtime.metrics)
BREAKER_STATE_CODES = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


class CircuitBreaker:
    """One worker's breaker: trips OPEN after `failure_threshold`
    CONSECUTIVE failures; after `cooldown_s` one half-open probe is allowed
    through — success closes the breaker, failure re-opens it (and restarts
    the cooldown)."""

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(f"failure_threshold must be >= 1: {failure_threshold}")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._lock = threading.Lock()
        self.state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None

    def allow(self) -> bool:
        """May a request go to this worker now?  An OPEN breaker past its
        cooldown transitions to HALF_OPEN and admits ONE probe."""
        with self._lock:
            if self.state == BREAKER_CLOSED:
                return True
            if self.state == BREAKER_HALF_OPEN:
                # one probe is already in flight; hold further traffic
                return False
            if self.clock() - self._opened_at >= self.cooldown_s:
                self.state = BREAKER_HALF_OPEN
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.state = BREAKER_CLOSED
            self._consecutive_failures = 0
            self._opened_at = None

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if (
                self.state == BREAKER_HALF_OPEN
                or self._consecutive_failures >= self.failure_threshold
            ):
                tripped = self.state != BREAKER_OPEN
                self.state = BREAKER_OPEN
                self._opened_at = self.clock()
            else:
                tripped = False
        if tripped:
            from trino_tpu.telemetry.metrics import breaker_trips_counter

            breaker_trips_counter().inc()

    def trip(self) -> None:
        """Force the breaker OPEN immediately (the heartbeat failure
        detector declared this worker DEAD: definitive evidence outranks
        the consecutive-failure count)."""
        with self._lock:
            tripped = self.state != BREAKER_OPEN
            self.state = BREAKER_OPEN
            self._consecutive_failures = max(
                self._consecutive_failures, self.failure_threshold
            )
            self._opened_at = self.clock()
        if tripped:
            from trino_tpu.telemetry.metrics import breaker_trips_counter

            breaker_trips_counter().inc()


class CircuitBreakerRegistry:
    """Worker url -> breaker; surfaced as the
    `trino_tpu_breaker_state{worker=...}` gauge in system.runtime.metrics.

    Knobs default to the typed config (`breaker.failure-threshold` /
    `breaker.cooldown` with per-worker `@token` overrides, trino_tpu/config);
    explicit constructor values — tests, embedded registries — win over
    config.  Breakers are created lazily per worker, so a config installed
    after import still applies to workers seen afterwards."""

    def __init__(self, failure_threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def _knobs_for(self, worker: str) -> tuple:
        """Each knob resolves independently: the explicit constructor value
        when given, the typed config (with per-worker overrides) otherwise
        — a registry pinning only one knob must not mute the config for
        the other."""
        cfg = None
        if self.failure_threshold is None or self.cooldown_s is None:
            from trino_tpu.config import get_config

            cfg = get_config().breaker_for(worker)
        threshold = (
            self.failure_threshold
            if self.failure_threshold is not None
            else cfg.failure_threshold
        )
        cooldown = (
            self.cooldown_s if self.cooldown_s is not None else cfg.cooldown_s
        )
        return threshold, cooldown

    def get(self, worker: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(worker)
            if b is None:
                threshold, cooldown = self._knobs_for(worker)
                b = CircuitBreaker(threshold, cooldown, self.clock)
                self._breakers[worker] = b
            return b

    def states(self) -> dict:
        with self._lock:
            return {w: b.state for w, b in self._breakers.items()}

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()


#: process-wide breakers for the multi-host HTTP tier (one per worker url)
BREAKERS = CircuitBreakerRegistry()


def execute_with_retry(
    fn,
    retry_policy: str = "NONE",
    max_attempts: int = 4,
    backoff: Optional[Backoff] = None,
):
    """Run fn() under the given retry policy (reference:
    SqlQueryExecution's retry handling for retry_policy=QUERY).  TASK-level
    retry happens inside the stage executor (parallel/runner.py); at this
    outer level it degrades to a final QUERY-style safety net.

    Retries wait behind capped exponential backoff with full jitter —
    back-to-back re-execution of a query that just failed hammers whatever
    made it fail.  Lifecycle aborts (cancel/deadline/memory-kill) are
    deliberately NOT in RETRYABLE: an aborted query must never re-run."""
    if max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1: {max_attempts}")
    if retry_policy == "NONE":
        return fn()
    assert retry_policy in ("QUERY", "TASK"), retry_policy
    if retry_policy == "TASK":
        # stage-level retry happens inside the stage executor; no outer
        # whole-query retries on top (reference: RetryPolicy.TASK)
        return fn()
    backoff = backoff or Backoff()
    last: Optional[BaseException] = None
    for attempt in range(max_attempts):
        if attempt:
            backoff.wait(attempt - 1)
        try:
            return fn()
        except RETRYABLE as e:
            last = e
    raise last
