"""Per-operator execution statistics (EXPLAIN ANALYZE backing).

Reference roles: operator/OperatorStats.java + OperationTimer (per-call
timing recorded from the Driver loop, Driver.java:298,340) and the
planprinter rendering of EXPLAIN ANALYZE.  Host-side generator wrappers time
each operator's batch production; device work is async under XLA dispatch, so
wall times are *inclusive* of the subtree's dispatch (noted in the rendering).
Under EXPLAIN ANALYZE each instrumented operator additionally BLOCKS on its
output batch (jax.block_until_ready) and records the wait as `device` time —
the host-feed vs device-compute split is a measured fact, at the cost of
serializing dispatch (measurement mode only; reference role: OperationTimer's
per-call CPU vs scheduled split in OperatorStats).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class OperatorStats:
    node_id: int
    name: str
    detail: str = ""
    output_rows: int = 0
    output_batches: int = 0
    wall_s: float = 0.0  # inclusive of upstream dispatch
    device_s: float = 0.0  # blocked-on-device time for THIS op's outputs
    depth: int = 0

    def line(self) -> str:
        pad = "  " * self.depth
        return (
            f"{pad}{self.name}[{self.detail}] rows={self.output_rows} "
            f"batches={self.output_batches} wall={self.wall_s * 1e3:.1f}ms "
            f"device={self.device_s * 1e3:.1f}ms"
        )


class StatsCollector:
    def __init__(self):
        self.operators: list[OperatorStats] = []
        self._next_id = 0
        #: per-query MemoryContext set by the execution planner so peak
        #: reservations render with the stats (MemoryPool visibility)
        self.memory = None

    def register(self, name: str, detail: str = "", depth: int = 0) -> OperatorStats:
        st = OperatorStats(self._next_id, name, detail, depth=depth)
        self._next_id += 1
        self.operators.append(st)
        return st

    def instrument(self, st: OperatorStats, stream):
        """Wrap a batch stream, recording rows/batches/wall per pull."""

        def gen():
            it = iter(stream)
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    st.wall_s += time.perf_counter() - t0
                    return
                t1 = time.perf_counter()
                st.wall_s += t1 - t0
                # block on THIS op's device work so the host-feed vs
                # device-compute split is attributed per operator
                try:
                    b.block_until_ready()
                except Exception:
                    pass
                st.device_s += time.perf_counter() - t1
                st.output_batches += 1
                st.output_rows += b.num_rows_host()
                yield b

        return gen()

    def render(self) -> str:
        # operators register in post-order (children first); reverse gives a
        # root-first rendering like the reference plan printer
        lines = [
            "Query execution statistics (wall = inclusive of subtree; "
            "device = blocked-on-device per op):"
        ]
        for st in reversed(self.operators):
            lines.append(st.line())
        total_dev = sum(st.device_s for st in self.operators)
        lines.append(f"total device-blocked: {total_dev * 1e3:.1f}ms")
        if self.memory is not None:
            lines.append(
                f"peak device memory reserved: {self.memory.peak} bytes"
            )
            from trino_tpu.runtime.buffer_pool import POOL

            s = POOL.stats()
            lines.append(
                "buffer pool: "
                f"device={s['device_bytes']}B hits={s['device_hits']} "
                f"misses={s['device_misses']}; host={s['host_bytes']}B"
            )
        return "\n".join(lines)
