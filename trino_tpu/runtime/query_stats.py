"""Per-operator execution statistics (EXPLAIN ANALYZE backing).

Reference roles: operator/OperatorStats.java + OperationTimer (per-call
timing recorded from the Driver loop, Driver.java:298,340) and the
planprinter rendering of EXPLAIN ANALYZE.  Host-side generator wrappers time
each operator's batch production; device work is async under XLA dispatch, so
wall times are *inclusive* of the subtree's dispatch (noted in the rendering).
Under EXPLAIN ANALYZE each instrumented operator additionally BLOCKS on its
output batch (jax.block_until_ready) and records the wait as `device` time —
the host-feed vs device-compute split is a measured fact, at the cost of
serializing dispatch (measurement mode only; reference role: OperationTimer's
per-call CPU vs scheduled split in OperatorStats).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from trino_tpu.telemetry import NULL_TRACER, now
from trino_tpu.telemetry.decisions import observe_collective
from trino_tpu.telemetry.metrics import (
    collective_bytes_counter,
    mesh_events_counter,
)


#: collective kinds that move bytes across the mesh interconnect — only
#: these bump the aggregate collective_bytes (pre-existing semantics:
#: all_to_all repartitions + all_gather broadcasts, now plus the psum
#: dynamic-filter reduce); "gather" attributions are host pulls
COLLECTIVE_KINDS = ("all_to_all", "all_gather", "reduce")

#: phase vocabulary of the mesh fragment profile (order = render order)
MESH_PHASES = ("trace", "compute", "collective", "transfer", "other")


@dataclass
class FragmentStats:
    """Per-fragment, per-phase breakdown of one distributed stage
    (reference role: StageStats / the per-stage rollup of OperatorStats).

    wall_s is the stage's SELF time (child-stage walls excluded); phases
    always sum to wall_s because `other` absorbs the untracked remainder,
    so `sum(phases) == wall` is an invariant, not an approximation."""

    fragment_id: int
    kind: str = ""
    wall_s: float = 0.0
    phases: dict = field(default_factory=lambda: {p: 0.0 for p in MESH_PHASES})
    #: bytes moved by this stage, by direction/kind
    bytes_to_device: int = 0
    bytes_to_host: int = 0
    collective_bytes: int = 0
    #: per-collective attribution: (kind, purpose) -> bytes.  Entries whose
    #: kind is a mesh collective (COLLECTIVE_KINDS) also land in
    #: collective_bytes, so the collective breakdown sums to the aggregate
    #: by construction (the Q3 "collective/expand bound" claim as a
    #: measured per-collective split, not one undifferentiated number).
    #: "gather" entries are host-side pulls — attributed here for the
    #: purpose split but NOT in collective_bytes (full-batch gathers are
    #: already counted in bytes_to_host; tiny capacity syncs never were).
    collective_by: dict = field(default_factory=dict)
    #: ISSUE-ordered (kind, purpose) sequence of this stage's mesh
    #: collectives (COLLECTIVE_KINDS only) — the observed half of the
    #: collective-uniformity contract: verify.device_residency compares it
    #: against the statically recorded signature (verify/collectives.py)
    collective_seq: list = field(default_factory=list)

    def close(self) -> None:
        tracked = sum(v for k, v in self.phases.items() if k != "other")
        self.phases["other"] = max(0.0, self.wall_s - tracked)

    def line(self) -> str:
        ph = " ".join(
            f"{k}={self.phases.get(k, 0.0) * 1e3:.1f}ms" for k in MESH_PHASES
        )
        by = ""
        if self.collective_by:
            by = " " + " ".join(
                f"{k}/{p}={b}"
                for (k, p), b in sorted(self.collective_by.items())
            )
        return (
            f"Fragment {self.fragment_id} [{self.kind}] "
            f"wall={self.wall_s * 1e3:.1f}ms {ph} "
            f"bytes(to_device={self.bytes_to_device} "
            f"to_host={self.bytes_to_host} "
            f"collective={self.collective_bytes}{by})"
        )

    def to_json(self) -> dict:
        return {
            "fragment": self.fragment_id,
            "kind": self.kind,
            "wall_s": round(self.wall_s, 4),
            "phases_ms": {
                k: round(v * 1e3, 2) for k, v in self.phases.items()
            },
            "bytes_to_device": self.bytes_to_device,
            "bytes_to_host": self.bytes_to_host,
            "collective_bytes": self.collective_bytes,
            "collective_bytes_by": {
                f"{k}/{p}": b
                for (k, p), b in sorted(self.collective_by.items())
            },
        }


class MeshProfile:
    """Per-query mesh execution profile: one FragmentStats per distributed
    stage plus query-wide transfer/trace counters.  `blocking=True` (EXPLAIN
    ANALYZE / bench) blocks on device results inside each phase so the
    breakdown measures device time, not dispatch time — measurement mode
    only, it serializes the async pipeline."""

    def __init__(self, blocking: bool = False, tracer=NULL_TRACER):
        self.blocking = blocking
        #: per-query span tracer (telemetry.spans): launch/transfer phases
        #: recorded here are also emitted as child spans of the enclosing
        #: fragment span; NULL_TRACER when tracing is off
        self.tracer = tracer
        self.fragments: dict[int, FragmentStats] = {}
        #: query-wide event counters: host_gather (device->host exchanges),
        #: host_restack (host->device re-stacks BETWEEN fragments — zero on
        #: the device-resident path), scan_cache_hit/miss
        self.counters: dict[str, int] = {}
        self.trace_hits = 0
        self.trace_misses = 0
        self.retraces = 0

    def fragment(self, fid: int) -> FragmentStats:
        st = self.fragments.get(fid)
        if st is None:
            st = self.fragments[fid] = FragmentStats(fid)
        return st

    def bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n
        # single-home mirror: every mesh event also lands in the process
        # metrics registry (served at /v1/metrics), labeled by counter name
        mesh_events_counter().labels(counter).inc(n)

    def add_collective(self, fid: int, nbytes: int, kind: str,
                       purpose: str) -> None:
        """Attribute collective/gather traffic: bumps the fragment's
        (kind, purpose) breakdown and the labeled
        trino_tpu_collective_bytes_total series, and — for mesh-collective
        kinds only — the aggregate collective_bytes.  ONE path, so the
        collective entries always sum to the aggregate, and host-side
        gathers (already in bytes_to_host) never inflate it."""
        st = self.fragment(fid)
        if kind in COLLECTIVE_KINDS:
            st.collective_bytes += nbytes
            st.collective_seq.append((kind, purpose))
        key = (kind, purpose)
        st.collective_by[key] = st.collective_by.get(key, 0) + nbytes
        collective_bytes_counter().labels(kind, purpose).inc(nbytes)
        # decision-ledger attribution (telemetry/decisions): the same
        # bytes, credited to the planner choice whose scope is active —
        # host-side bookkeeping on an int the profile already holds
        observe_collective(fid, nbytes, kind, purpose)

    def collective_sequences(self) -> dict:
        """{fragment id: ((kind, purpose), ...)} of mesh collectives in
        issue order (the shape signature_problems compares)."""
        return {
            fid: tuple(st.collective_seq)
            for fid, st in self.fragments.items()
            if st.collective_seq
        }

    @contextmanager
    def phase(self, fid: int, name: str):
        """Time a phase of fragment `fid` (caller blocks inside the window
        when self.blocking, so the phase measures device time)."""
        t0 = now()
        try:
            yield
        finally:
            t1 = now()
            self.add_phase(fid, name, t1 - t0)
            if self.tracer.enabled:
                self.tracer.record(name, t0, t1, {"fragment": fid})

    def add_phase(self, fid: int, name: str, seconds: float) -> None:
        st = self.fragment(fid)
        st.phases[name] = st.phases.get(name, 0.0) + seconds

    def collective_totals(self) -> dict:
        """Query-wide (kind, purpose) -> bytes summed over fragments."""
        totals: dict = {}
        for st in self.fragments.values():
            for key, b in st.collective_by.items():
                totals[key] = totals.get(key, 0) + b
        return totals

    def phase_totals(self) -> dict:
        """Query-wide per-phase seconds summed over fragments (the
        QueryStatistics payload event listeners receive)."""
        totals: dict[str, float] = {}
        for st in self.fragments.values():
            for k, v in st.phases.items():
                totals[k] = totals.get(k, 0.0) + v
        return {k: round(v, 6) for k, v in totals.items()}

    def render(self) -> str:
        lines = [
            "Mesh execution profile (per-fragment; wall = stage self time):"
        ]
        for fid in sorted(self.fragments):
            lines.append("  " + self.fragments[fid].line())
        lines.append(
            "  trace cache: "
            f"hits={self.trace_hits} misses={self.trace_misses} "
            f"retraces={self.retraces}"
        )
        if self.counters:
            lines.append(
                "  transfers: "
                + " ".join(
                    f"{k}={v}" for k, v in sorted(self.counters.items())
                )
            )
        coll = self.collective_totals()
        if coll:
            lines.append(
                "  collective bytes: "
                + " ".join(
                    f"{k}/{p}={b}" for (k, p), b in sorted(coll.items())
                )
            )
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "fragments": [
                self.fragments[fid].to_json()
                for fid in sorted(self.fragments)
            ],
            "trace_cache": {
                "hits": self.trace_hits,
                "misses": self.trace_misses,
                "retraces": self.retraces,
            },
            "counters": dict(self.counters),
            "collective_bytes_by": {
                f"{k}/{p}": b
                for (k, p), b in sorted(self.collective_totals().items())
            },
        }


@dataclass
class OperatorStats:
    node_id: int
    name: str
    detail: str = ""
    output_rows: int = 0
    output_batches: int = 0
    wall_s: float = 0.0  # inclusive of upstream dispatch
    device_s: float = 0.0  # blocked-on-device time for THIS op's outputs
    depth: int = 0

    def line(self) -> str:
        pad = "  " * self.depth
        return (
            f"{pad}{self.name}[{self.detail}] rows={self.output_rows} "
            f"batches={self.output_batches} wall={self.wall_s * 1e3:.1f}ms "
            f"device={self.device_s * 1e3:.1f}ms"
        )


class StatsCollector:
    def __init__(self):
        self.operators: list[OperatorStats] = []
        self._next_id = 0
        #: per-query MemoryContext set by the execution planner so peak
        #: reservations render with the stats (MemoryPool visibility)
        self.memory = None
        #: MeshProfile attached by the distributed runner so EXPLAIN ANALYZE
        #: renders the per-fragment collective/compute/transfer breakdown
        self.mesh_profile = None
        #: local-execution pressure counters (memory_wave / spill_bytes),
        #: bumped by runtime/spill's PressureObserver so EXPLAIN ANALYZE
        #: shows the degradation a constrained query took
        self.counters: dict = {}

    def bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    def register(self, name: str, detail: str = "", depth: int = 0) -> OperatorStats:
        st = OperatorStats(self._next_id, name, detail, depth=depth)
        self._next_id += 1
        self.operators.append(st)
        return st

    def instrument(self, st: OperatorStats, stream):
        """Wrap a batch stream, recording rows/batches/wall per pull."""

        def gen():
            it = iter(stream)
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    st.wall_s += time.perf_counter() - t0
                    return
                t1 = time.perf_counter()
                st.wall_s += t1 - t0
                # block on THIS op's device work so the host-feed vs
                # device-compute split is attributed per operator
                try:
                    b.block_until_ready()
                except Exception:
                    pass
                st.device_s += time.perf_counter() - t1
                st.output_batches += 1
                st.output_rows += b.num_rows_host()
                yield b

        return gen()

    def render(self) -> str:
        # operators register in post-order (children first); reverse gives a
        # root-first rendering like the reference plan printer
        lines = [
            "Query execution statistics (wall = inclusive of subtree; "
            "device = blocked-on-device per op):"
        ]
        if self.mesh_profile is not None:
            lines.append(self.mesh_profile.render())
        for st in reversed(self.operators):
            lines.append(st.line())
        total_dev = sum(st.device_s for st in self.operators)
        lines.append(f"total device-blocked: {total_dev * 1e3:.1f}ms")
        if self.counters:
            lines.append(
                "memory pressure: "
                + " ".join(
                    f"{k}={v}" for k, v in sorted(self.counters.items())
                )
            )
        if self.memory is not None:
            lines.append(
                f"peak device memory reserved: {self.memory.peak} bytes"
            )
            from trino_tpu.runtime.buffer_pool import POOL

            s = POOL.stats()
            lines.append(
                "buffer pool: "
                f"device={s['device_bytes']}B hits={s['device_hits']} "
                f"misses={s['device_misses']}; host={s['host_bytes']}B"
            )
        return "\n".join(lines)
