"""Restart resilience: persistent compile cache + AOT prewarm executor.

Reference role: the generated-bytecode / plan caching that lets a restarted
Trino worker serve at speed immediately (SURVEY §7) — an XLA-backed engine's
analog has two halves, because its cold cost has two layers:

  * the **XLA compile** (the expensive half: Q6 SF10 mesh-8 is 76.6 s cold
    vs 12.7 s warm) persists across restarts via JAX's native on-disk
    compilation cache — `enable_persistent_compile_cache` wires the
    CompileCache config section (trino_tpu/config) through the filesystem
    SPI into `jax_compilation_cache_dir`, with a graceful no-op when the
    backend doesn't support it.  A restarted worker re-traces but reloads
    executables from disk.
  * the **trace** (`spmd.TRACE_CACHE` is process-local and dies with the
    process) is re-done by the `PrewarmExecutor`: it persists a workload
    manifest — the SQL replay set, the learned speculative-join capacities
    (`cap_history`), and the recorder's closure watermark — via the same
    filesystem SPI, and replays it in a background thread at server start
    and after `add_worker` grows the mesh, re-tracing every (step, bucket,
    mesh) key at the CURRENT mesh signature before the next query arrives.

Closure is verified, not assumed: after the replay the executor takes an
observatory watermark and (when `verify`) replays once more — zero compile
events above the watermark means the key set is closed and the first real
query compiles nothing.  State is surfaced in `system.runtime.nodes`
(`prewarm` column) and the `trino_tpu_prewarm_*` metric family;
`tools/prewarm_manifest.py` is the CLI for recording manifests offline.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1

#: bounded replay set: a serving coordinator records distinct SELECTs here,
#: and an unbounded set would make prewarm replay unbounded too
RECORD_LIMIT = 512

#: a statement that LEARNS a speculative-join capacity legitimately compiles
#: again on its next run (the fused expand moves to the learned bucket);
#: bound the follow-up runs so a pathological workload cannot loop
MAX_CAPACITY_ROUNDS = 4


# -- persistent XLA compile cache ----------------------------------------------


def enable_persistent_compile_cache(cfg=None, warn=None) -> Optional[str]:
    """Apply the CompileCache config section to JAX's native on-disk
    compilation cache; returns the local directory in effect, or None when
    disabled or gracefully degraded (remote filesystem scheme without an
    implementation, a jax build without the knob, or an unwritable dir —
    a missing cache is slower, never wrong, so configuration problems warn
    instead of failing server bring-up)."""
    from trino_tpu.config import get_config

    cc = (cfg or get_config()).compile_cache
    emit = warn or log.warning
    if not cc.enabled or not cc.dir:
        return None
    from trino_tpu.filesystem import filesystem_for, strip_scheme

    try:
        fs = filesystem_for(cc.dir)
    except NotImplementedError as e:
        emit(f"persistent compile cache disabled: {e}")
        return None
    path = strip_scheme(cc.dir)
    try:
        fs.mkdirs(path)
    except OSError as e:
        emit(f"persistent compile cache disabled: cannot create {path}: {e}")
        return None
    from trino_tpu.parallel.spmd import configure_persistent_cache

    if not configure_persistent_cache(
        path, cc.min_compile_time_s, cc.min_entry_size_bytes
    ):
        emit(
            "persistent compile cache disabled: this jax build has no "
            "jax_compilation_cache_dir knob"
        )
        return None
    return path


def disable_persistent_compile_cache() -> None:
    """Detach the on-disk cache (tests; a tmpdir cache must not outlive
    its directory)."""
    from trino_tpu.parallel.spmd import configure_persistent_cache

    configure_persistent_cache(None)


# -- workload manifest ---------------------------------------------------------


@dataclass
class WorkloadManifest:
    """What a process must replay to be warm: the SQL set, the learned
    capacities that make speculative joins take the fused path at the
    right bucket on run 1, and the recorder's closure evidence."""

    statements: list = field(default_factory=list)
    cap_history: list = field(default_factory=list)
    #: recorder's compile-event count once its key set closed (its own
    #: process counter — a replaying process derives its OWN watermark)
    watermark: int = 0
    #: recorder verified a replay added zero events above the watermark
    closed: Optional[bool] = None
    workers: int = 0
    #: the observatory's deduplicated key set at save (informational: which
    #: steps/buckets the replay is expected to trace)
    compile_keys: list = field(default_factory=list)
    #: global dictionary snapshot document (runtime/dictionary_service
    #: snapshot_doc): shipped with the manifest so a restarted process
    #: resolves versioned code assignments BEFORE replaying — warm paths
    #: never block on (or re-derive differently-versioned) code resolution
    dictionaries: Optional[dict] = None

    def to_json(self) -> dict:
        return {
            "version": MANIFEST_VERSION,
            "sql": list(self.statements),
            "cap_history": list(self.cap_history),
            "watermark": self.watermark,
            "closed": self.closed,
            "workers": self.workers,
            "manifest": list(self.compile_keys),
            "dictionaries": self.dictionaries,
        }

    @classmethod
    def from_json(cls, doc: dict) -> "WorkloadManifest":
        """Tolerant load: tools/prewarm_manifest.py documents (which carry
        extra bench fields) and hand-written {"sql": [...]} files both
        work — a manifest is an optimization input, never a schema
        contract that bricks a restart."""
        return cls(
            statements=list(doc.get("sql") or ()),
            cap_history=list(doc.get("cap_history") or ()),
            watermark=int(doc.get("watermark") or 0),
            closed=doc.get("closed"),
            workers=int(doc.get("workers") or 0),
            compile_keys=list(doc.get("manifest") or ()),
            dictionaries=(
                doc.get("dictionaries")
                if isinstance(doc.get("dictionaries"), dict)
                else None
            ),
        )


def save_manifest(manifest: WorkloadManifest, location: str,
                  extra: Optional[dict] = None) -> None:
    """Persist via the filesystem SPI (atomic publish — a reader never
    sees a half-written manifest)."""
    from trino_tpu.filesystem import filesystem_for, strip_scheme

    fs = filesystem_for(location)
    doc = manifest.to_json()
    if extra:
        doc.update(extra)
    fs.write(
        strip_scheme(location),
        (json.dumps(doc, indent=1, default=str) + "\n").encode(),
    )


def load_manifest(location: str) -> Optional[WorkloadManifest]:
    """Load, or None when absent/unreadable (a fresh deployment has no
    manifest yet; prewarm simply has nothing to do)."""
    from trino_tpu.filesystem import filesystem_for, strip_scheme

    try:
        fs = filesystem_for(location)
        path = strip_scheme(location)
        if not fs.exists(path):
            return None
        return WorkloadManifest.from_json(json.loads(fs.read(path).decode()))
    except (NotImplementedError, OSError, ValueError) as e:
        log.warning("prewarm manifest unreadable at %s: %s", location, e)
        return None


def replay_statements(runner, statements,
                      max_capacity_rounds: int = MAX_CAPACITY_ROUNDS) -> int:
    """Run each statement once, plus one bounded follow-up per run that
    LEARNED a speculative-join capacity (CAP_HISTORY.version moved): the
    next run compiles the fused expand at the learned bucket, which is part
    of the closed key set, not a closure failure.  Returns executions."""
    from trino_tpu.partitioning import CAP_HISTORY

    runs = 0
    for sql in statements:
        version = CAP_HISTORY.version
        runner.execute(sql)
        runs += 1
        extra = 0
        while CAP_HISTORY.version != version and extra < max_capacity_rounds:
            version = CAP_HISTORY.version
            runner.execute(sql)
            runs += 1
            extra += 1
    return runs


def _is_replayable(sql: str) -> bool:
    """Only read-only statements belong in a replay set: replaying DDL/DML
    would mutate state, and SET SESSION would leak into later queries."""
    head = sql.lstrip().lower()
    return head.startswith(("select", "with", "values", "table "))


# -- prewarm executor ----------------------------------------------------------


class PrewarmExecutor:
    """Replays a persisted workload manifest on a runner so its compile-key
    set is warm before real traffic arrives (see module doc).

    States: IDLE (no manifest / nothing replayed), RUNNING (replay in
    flight), WARM (replayed AND verified closed), UNCLOSED (the verify
    replay still compiled — the manifest under-covers the workload),
    FAILED (a replay statement raised).  `watermark` is the observatory
    count taken right after the replay: the closure assertion for THIS
    process is `OBSERVATORY.mark() - watermark == 0` after any further
    replay of the manifest."""

    def __init__(self, runner, manifest_location: Optional[str] = None,
                 verify: bool = True, lock: Optional[threading.Lock] = None):
        from trino_tpu.config import get_config

        self.runner = runner
        self.location = (
            manifest_location
            if manifest_location is not None
            else (get_config().prewarm.manifest_path or None)
        )
        self.verify = verify
        #: serializes replays against real queries — a server passes its
        #: engine lock so prewarm never interleaves with a statement on the
        #: shared (not concurrency-safe) runner
        self._engine_lock = lock or threading.Lock()
        #: dispatcher-mode admission (use_admission): a factory returning a
        #: context manager that admits the replay through the weight-capped
        #: system.prewarm resource group onto the primary engine lane —
        #: replays become fair queue participants instead of lock holders
        self._admission = None
        self._state_lock = threading.Lock()
        self.state = "IDLE"
        #: observatory count at closure (None until a replay completed)
        self.watermark: Optional[int] = None
        #: compile events the last verify replay recorded above the
        #: watermark (0 = closed; the acceptance assertion)
        self.verify_events: Optional[int] = None
        self.last_error: Optional[str] = None
        self.runs = 0
        self._recorded: list = []
        self._recorded_set: set = set()
        self._thread: Optional[threading.Thread] = None
        #: a kick that arrived while a replay was in flight (latest wins);
        #: the finishing replay starts it, so a grow during a start replay
        #: still re-traces at the final mesh signature
        self._pending: Optional[tuple] = None

    def use_lock(self, lock: threading.Lock) -> None:
        """Adopt a server's engine lock so replays serialize with live
        queries on the shared (not concurrency-safe) runner.  The
        CoordinatorServer calls this when it adopts a pre-attached
        executor (e.g. one runner_from_etc created); call before the
        first replay — an in-flight replay keeps the lock it started
        with."""
        self._engine_lock = lock

    def use_admission(self, factory) -> None:
        """Adopt a dispatcher admission (CoordinatorServer passes
        `dispatcher.system_admission`): replays serialize with live
        queries by admitting through the system.prewarm resource group
        instead of holding a lock — a post-grow replay waits its fair
        turn and other engine lanes keep serving users meanwhile.
        Supersedes use_lock when set."""
        self._admission = factory

    def _serialized(self):
        """The context manager one replay runs under (admission when a
        dispatcher adopted us, the engine lock otherwise)."""
        return (
            self._admission() if self._admission is not None
            else self._engine_lock
        )

    # -- recording (the serving-path manifest source) -------------------------

    def record(self, sql: str) -> bool:
        """Add a statement to the replay set (deduplicated, first-seen
        order, read-only statements only, bounded)."""
        if not _is_replayable(sql):
            return False
        with self._state_lock:
            if sql in self._recorded_set or len(self._recorded) >= RECORD_LIMIT:
                return False
            self._recorded.append(sql)
            self._recorded_set.add(sql)
        return True

    def manifest(self) -> WorkloadManifest:
        """A manifest of everything recorded in THIS process, with the
        current learned capacities and observatory state."""
        from trino_tpu.partitioning import CAP_HISTORY
        from trino_tpu.runtime.dictionary_service import DICTIONARY_SERVICE
        from trino_tpu.telemetry.compile_events import OBSERVATORY

        with self._state_lock:
            stmts = list(self._recorded)
        from trino_tpu.config import get_config

        dicts = DICTIONARY_SERVICE.snapshot_doc(
            get_config().dictionary.max_inline_values
        )
        return WorkloadManifest(
            statements=stmts,
            cap_history=CAP_HISTORY.snapshot(),
            watermark=OBSERVATORY.mark(),
            closed=None,
            workers=getattr(getattr(self.runner, "wm", None), "n", 0)
            or len(getattr(self.runner, "worker_urls", ())),
            compile_keys=OBSERVATORY.manifest(),
            dictionaries=dicts if dicts.get("entries") else None,
        )

    def save(self) -> bool:
        """Persist the UNION of the on-disk manifest and this process's
        recorded statements (no-op without a location or anything new to
        add).  Merging at save time — not only when a replay happened to
        load the file — means an operator-provided manifest survives even
        a server that shut down before its prewarm ran or had
        `prewarm.on-start=false`."""
        if not self.location:
            return False
        m = self.manifest()
        existing = self.load()
        if existing is not None and existing.statements:
            seen = set(existing.statements)
            m.statements = existing.statements + [
                s for s in m.statements if s not in seen
            ]
        if not m.statements:
            return False
        save_manifest(m, self.location)
        return True

    def load(self) -> Optional[WorkloadManifest]:
        return load_manifest(self.location) if self.location else None

    # -- replay ----------------------------------------------------------------

    def run(self, reason: str = "manual", wait: bool = False,
            statements: Optional[list] = None) -> Optional[threading.Thread]:
        """Replay in a background thread, one at a time.  A kick arriving
        while a replay is in flight is QUEUED (latest wins) and started by
        the finishing replay — a grow racing a start replay must still get
        a replay at the final mesh signature, never be silently dropped.
        `wait=True` joins the replay (and the queued follow-up, if any)."""
        with self._state_lock:
            t = self._thread
            if t is not None and t.is_alive():
                self._pending = (reason, statements)
            else:
                t = self._spawn(reason, statements)
        if wait:
            t.join()
            with self._state_lock:
                follow = self._thread
            if follow is not None and follow is not t:
                follow.join()
        return t

    def _spawn(self, reason: str, statements: Optional[list]):  # lint: allow(unguarded-state)
        """Start a replay thread (caller holds _state_lock)."""
        t = threading.Thread(
            target=self._replay, args=(reason, statements),
            daemon=True, name=f"prewarm-{reason}",
        )
        self._thread = t
        t.start()
        return t

    def _set_state(self, state: str) -> None:
        from trino_tpu.telemetry.metrics import (
            PREWARM_STATE_CODES,
            prewarm_state_gauge,
        )

        with self._state_lock:
            self.state = state
        prewarm_state_gauge().set(PREWARM_STATE_CODES.get(state, 0))

    def _replay(self, reason: str, statements: Optional[list]) -> None:
        from trino_tpu.partitioning import CAP_HISTORY
        from trino_tpu.telemetry.compile_events import OBSERVATORY
        from trino_tpu.telemetry.metrics import (
            prewarm_runs_counter,
            prewarm_statements_counter,
        )

        self._set_state("RUNNING")
        outcome = "failed"
        try:
            stmts = statements
            if stmts is None:
                m = self.load()
                if m is not None:
                    stmts = m.statements
                    # seed learned capacities FIRST so capacity-learning
                    # statements take the fused path at the right bucket on
                    # run 1 and the key set closes without extra rounds
                    CAP_HISTORY.seed(m.cap_history)
                    # adopt the recorded global dictionary assignment BEFORE
                    # replaying: the replay re-registers connector
                    # dictionaries under the RECORDED versions, so refs and
                    # compiled traces from before the restart stay valid
                    if m.dictionaries:
                        from trino_tpu.runtime.dictionary_service import (
                            DICTIONARY_SERVICE,
                        )

                        DICTIONARY_SERVICE.load_doc(m.dictionaries)
                    # the loaded set joins the recorded set: a restarted
                    # server's save() persists the UNION of the seed
                    # manifest and this incarnation's observed statements
                    for s in stmts:
                        self.record(s)
            if not stmts:
                outcome = "empty"
                self._set_state("IDLE")
                return
            with self._serialized():
                n = replay_statements(self.runner, stmts)
                prewarm_statements_counter().inc(n)
                wm = OBSERVATORY.mark()
                with self._state_lock:
                    self.watermark = wm
                if self.verify:
                    # closure is MEASURED: one more replay must record zero
                    # compile events above the watermark (capacity learning
                    # is settled by now, so no follow-up rounds)
                    prewarm_statements_counter().inc(
                        replay_statements(
                            self.runner, stmts, max_capacity_rounds=0
                        )
                    )
                    above = OBSERVATORY.mark() - wm
                    with self._state_lock:
                        self.verify_events = above
                    if above:
                        leaks = sorted(
                            {e.step for e in OBSERVATORY.events_above(wm)}
                        )
                        log.warning(
                            "prewarm replay is not closed: %d compile "
                            "event(s) above the watermark (steps: %s)",
                            above, ", ".join(leaks) or "rotated out of ring",
                        )
                        outcome = "unclosed"
                        self._set_state("UNCLOSED")
                        return
            outcome = "warm"
            self._set_state("WARM")
        except Exception as e:
            msg = f"{type(e).__name__}: {e}"
            with self._state_lock:
                self.last_error = msg
            log.warning("prewarm replay failed: %s", msg)
            self._set_state("FAILED")
        finally:
            self.runs += 1
            prewarm_runs_counter().labels(
                reason if reason in ("start", "grow") else "manual", outcome
            ).inc()
            # a kick queued while we ran replays now, at the CURRENT state
            # (e.g. the final mesh signature after a grow raced us)
            with self._state_lock:
                pending, self._pending = self._pending, None
                if pending is not None:
                    self._spawn(*pending)


def attach_prewarm(runner, manifest_location: Optional[str] = None,
                   **kw) -> Optional[PrewarmExecutor]:
    """Create + attach a PrewarmExecutor as `runner.prewarm` when a
    manifest location is configured (arg or `prewarm.manifest-path`);
    returns it, or None when unconfigured.  Grow paths
    (DistributedQueryRunner.resize_mesh / MultiHostQueryRunner.add_worker)
    and server start consult the attribute."""
    from trino_tpu.config import get_config

    loc = manifest_location or get_config().prewarm.manifest_path
    if not loc:
        return None
    runner.prewarm = PrewarmExecutor(runner, loc, **kw)
    return runner.prewarm


def kick_grow_prewarm(runner) -> Optional[threading.Thread]:
    """After a mesh grow: replay the manifest at the NEW mesh signature in
    the background (PR 7 gap (d)).  No-op without an attached executor or
    with `prewarm.on-grow=false`."""
    from trino_tpu.config import get_config

    pw = getattr(runner, "prewarm", None)
    if pw is None or not get_config().prewarm.on_grow:
        return None
    return pw.run(reason="grow")
