"""Execution runtime (reference: core/trino-main/.../execution/**)."""
