"""Background prefetch for the scan feed.

Reference role: execution/executor/TaskExecutor.java's overlap of IO-bound
split reads with compute — here a feed thread runs host-side page decode,
padding, and `jax.device_put` of batch k+1 while the main thread's XLA step
for batch k executes (device dispatch is async, so the two genuinely
overlap).  SURVEY.md §7's feed/step/drain pipeline.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

_SENTINEL = object()


def eager_prefetch(source: Iterable, depth: int = 2) -> Iterator:
    """Like prefetch_iter but the producer thread starts NOW, not at the
    first next() — the pipeline-parallelism seam (reference: §2.7(4)
    build/probe overlap): a probe side wrapped eagerly decodes and feeds
    while the join's build side is still indexing on device.

    Shares prefetch_iter's producer (stop Event + finally-drain), so an
    abandoned consumer (LIMIT, planning failure after the join visit) stops
    the thread instead of leaving it blocked on a full queue."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def run():
        try:
            for item in source:
                if stop.is_set():
                    return
                q.put(item)
        except BaseException as e:  # propagate to consumer
            q.put((_SENTINEL, e))
            return
        q.put(_SENTINEL)

    t = threading.Thread(target=run, daemon=True, name="eager-prefetch")
    t.start()

    def drain():
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    return
                if (
                    isinstance(item, tuple)
                    and len(item) == 2
                    and item[0] is _SENTINEL
                ):
                    raise item[1]
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    return drain()


def prefetch_iter(source: Iterable, depth: int = 2) -> Iterator:
    """Iterate `source` in a daemon thread, keeping up to `depth` results
    ready.  Exceptions in the producer re-raise at the consuming point."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()

    def run():
        try:
            for item in source:
                if stop.is_set():
                    return
                q.put(item)
        except BaseException as e:  # propagate to consumer
            q.put((_SENTINEL, e))
            return
        q.put(_SENTINEL)

    t = threading.Thread(target=run, daemon=True, name="scan-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _SENTINEL:
                raise item[1]
            yield item
    finally:
        stop.set()
        # drain so a blocked producer can observe `stop` and exit
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
