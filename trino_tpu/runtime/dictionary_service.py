"""Global dictionary service: versioned mesh-wide string codes.

Dictionary codes used to be producer-local (code == local sort rank), which
forced `partitioning/properties.hash_aligned_criteria` to exclude string
keys from every cross-side placement claim and forced exchanges to ship and
re-unify dictionary VALUES.  This service makes the code assignment a
coordinator-owned, versioned fact per (catalog, schema, table, column):

  * **assignment** — connectors register the one dictionary their string
    column is coded against (`Connector.global_dictionary`); registration is
    idempotent by fingerprint, and a re-registration that APPENDS values is
    a version bump under which every existing code keeps its meaning (the
    append-only contract that keeps cached scans and compiled traces keyed
    by dictionary identity valid).  A rewrite that re-maps codes (e.g. the
    memory connector's sorted-union append) is still a version bump, but a
    `remap` one: claims are keyed on exact (key, version), so stale-version
    data can never silently co-locate with new codes.
  * **snapshot** — `save_snapshot`/`load_snapshot` persist the assignment
    atomically through the filesystem SPI (the SpoolManager/manifest
    pattern); `snapshot_doc` inlines it into the PR 8 prewarm manifest so a
    restarted coordinator (and every prewarming worker) resolves codes
    before the first real query, never blocking a warm path.  A missing or
    torn snapshot degrades LOUDLY to producer-local codes — slower plans
    (exchanges come back), never wrong results.
  * **resolution** — exchanges ship `(key, version)` refs instead of
    dictionary values (`parallel/serde`); a receiver resolves refs locally,
    by re-asking its own connectors (generated catalogs are deterministic),
    or through the coordinator's `GET /v1/dictionary/...` endpoint.
  * **claims** — `coding(handle, column, catalogs)` is what the planner and
    verifier consult: two join sides whose key symbols map to the SAME
    (key, version) provably place equal strings on equal workers, so the
    placer may lift the dictionary exclusion and co-locate varchar keys
    like integer keys.  `unique` entries (null-free bijections such as the
    TPC-DS `*_id` business keys) are additionally admissible as
    `exact_distinct` uniqueness sources for capacity certificates.

Late materialization falls out of the existing engine shape: device kernels
only ever see i32 codes, and values are materialized from the (shared)
dictionary at result gather.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from trino_tpu.columnar.dictionary import (
    PatternDictionary,
    StringDictionary,
    UnorderedDictionary,
)

log = logging.getLogger(__name__)

SNAPSHOT_VERSION = 1

#: a dictionary larger than this is snapshotted as metadata only (the
#: connector re-adopts its recorded version on re-registration) — the
#: snapshot is a restart artifact, not a data lake
DEFAULT_MAX_INLINE_VALUES = 1 << 16


def dictionary_fingerprint(d: StringDictionary) -> tuple:
    """Cheap content identity: pattern dictionaries by (pattern_key, n)
    (never materializing the lazy values), materialized ones by the cached
    value-tuple hash."""
    if isinstance(d, PatternDictionary):
        return ("pattern", str(d.pattern_key), len(d.values))
    return ("values", len(d.values), hash(d))


@dataclass
class DictionaryEntry:
    """One immutable (key, version) assignment."""

    key: tuple  # (catalog, schema, table, column)
    version: int
    dictionary: StringDictionary
    #: null-free bijection over the table's rows (code space == row space):
    #: admissible as an exact_distinct uniqueness source (verify.capacity)
    unique: bool = False
    fingerprint: tuple = ()
    #: False for append bumps (codes of the prior version keep their
    #: meaning), True when the registration re-mapped codes (memory
    #: connector rewrites) — consumers key claims on exact versions either
    #: way, this is bookkeeping for tests/operators
    remap: bool = False

    @property
    def ref(self) -> tuple:
        return (self.key, self.version)


def _is_extension(old: StringDictionary, new: StringDictionary) -> bool:
    """True when `new` appends to `old` (old codes keep their meaning)."""
    if len(new.values) < len(old.values):
        return False
    if isinstance(old, PatternDictionary) and isinstance(new, PatternDictionary):
        # same monotone generator, more rows: a prefix by construction
        return old.pattern_key == new.pattern_key
    if isinstance(old, PatternDictionary) or isinstance(new, PatternDictionary):
        return False  # don't materialize a lazy sequence to compare
    return tuple(new.values[: len(old.values)]) == tuple(old.values)


class GlobalDictionaryService:
    """Process-wide registry of versioned global code assignments.

    Thread-safe; the coordinator owns the authoritative instance and
    workers hold replicas fed by snapshots, connector re-registration, or
    the coordinator resolution endpoint."""

    def __init__(self):
        self._lock = threading.RLock()
        #: (key, version) -> DictionaryEntry (ALL versions stay resolvable)
        self._entries: dict[tuple, DictionaryEntry] = {}
        #: key -> latest version number
        self._latest: dict[tuple, int] = {}
        #: fingerprint -> ref, for serde's reverse lookup (any equal
        #: dictionary resolves the ref, so collisions across keys are fine)
        self._by_fp: dict[tuple, tuple] = {}
        #: key -> {fingerprint-repr: (version, unique)} adopted from a
        #: metadata-only snapshot entry: a later registration with the same
        #: fingerprint takes the RECORDED version so refs shipped before
        #: the restart stay valid
        self._adopt: dict[tuple, dict] = {}
        #: optional callable (key, version) -> StringDictionary | None used
        #: when a shipped ref is not locally resolvable (HTTP workers point
        #: this at the coordinator's /v1/dictionary endpoint)
        self.fetch_hook = None
        #: catalogs consulted for lazy registration during resolution
        self._catalogs = None

    # -- registration ----------------------------------------------------------

    def attach_catalogs(self, catalogs) -> None:
        """Catalogs used to lazily (re-)register dictionaries during ref
        resolution (worker processes resolving generated-table refs)."""
        self._catalogs = catalogs

    def register(self, catalog: str, schema: str, table: str, column: str,
                 dictionary: StringDictionary, unique: bool = False
                 ) -> DictionaryEntry:
        """Idempotent by fingerprint; a changed dictionary bumps the
        version (append-only when it extends the previous one)."""
        key = (catalog, schema, table, column)
        fp = dictionary_fingerprint(dictionary)
        with self._lock:
            latest = self._latest.get(key)
            if latest is not None:
                cur = self._entries[(key, latest)]
                if cur.fingerprint == fp:
                    if unique and not cur.unique:
                        cur.unique = True
                    return cur
            adopted = self._adopt.get(key, {}).pop(repr(fp), None)
            if adopted is not None:
                version, rec_unique = adopted
                unique = unique or rec_unique
            else:
                version = (latest or 0) + 1
                # never collide with a version recorded in a snapshot
                for v, _ in self._adopt.get(key, {}).values():
                    version = max(version, v + 1)
            remap = False
            if latest is not None:
                remap = not _is_extension(
                    self._entries[(key, latest)].dictionary, dictionary
                )
                version = max(version, latest + 1)
            ent = DictionaryEntry(key, version, dictionary, unique, fp, remap)
            self._entries[(key, version)] = ent
            self._latest[key] = max(latest or 0, version)
            self._by_fp[fp] = ent.ref
            return ent

    def extend(self, key: tuple, new_values) -> DictionaryEntry:
        """Append-only version bump: existing codes NEVER re-map.  The
        result is unordered past the original prefix, so order-dependent
        dictionary operations (range predicates, LIKE prefix ranges) raise
        instead of silently misordering — appended epochs serve equality
        joins/group-bys and late materialization only."""
        with self._lock:
            latest = self._latest.get(tuple(key))
            if latest is None:
                raise KeyError(f"no dictionary registered for {key}")
            cur = self._entries[(tuple(key), latest)]
            old = tuple(cur.dictionary.values)
            seen = set(old)
            appended = [v for v in new_values if v not in seen]
            if not appended:
                return cur
            d = UnorderedDictionary(old + tuple(appended))
            ent = DictionaryEntry(
                tuple(key), latest + 1, d, False, dictionary_fingerprint(d)
            )
            self._entries[ent.ref] = ent
            self._latest[tuple(key)] = ent.version
            self._by_fp[ent.fingerprint] = ent.ref
            return ent

    # -- lookup ----------------------------------------------------------------

    def lookup(self, handle, column: str, catalogs=None
               ) -> Optional[DictionaryEntry]:
        """Latest entry for a scan column, consulting the connector for
        lazy (re-)registration when catalogs are available.  Returns None
        when the column has no global assignment (producer-local codes)."""
        catalogs = catalogs if catalogs is not None else self._catalogs
        if catalogs is not None:
            try:
                conn = catalogs.get(handle.catalog)
            except KeyError:
                conn = None
            if conn is not None:
                got = conn.global_dictionary(handle, column)
                if got is not None:
                    d, unique = got
                    return self.register(
                        handle.catalog, handle.schema, handle.table, column,
                        d, unique,
                    )
        key = (handle.catalog, handle.schema, handle.table, column)
        with self._lock:
            latest = self._latest.get(key)
            if latest is None:
                return None
            return self._entries[(key, latest)]

    def coding(self, handle, column: str, catalogs=None) -> Optional[tuple]:
        """(key, version) ref the column's codes are assigned under, or
        None — the planner/verifier claim gate."""
        ent = self.lookup(handle, column, catalogs)
        return None if ent is None else ent.ref

    def ref_of(self, dictionary: StringDictionary) -> Optional[tuple]:
        """Reverse lookup for serde: a ref whose entry holds an EQUAL
        dictionary, or None (producer-local — ship values)."""
        if dictionary is None:
            return None
        fp = dictionary_fingerprint(dictionary)
        with self._lock:
            return self._by_fp.get(fp)

    def entry(self, key, version: int) -> DictionaryEntry:
        """Exact (key, version) entry, consulting connectors for lazy
        re-registration (the coordinator resolution endpoint's lookup);
        raises KeyError when the exact version is unknown."""
        key = tuple(key)
        with self._lock:
            ent = self._entries.get((key, version))
        if ent is not None:
            return ent
        if self._catalogs is not None:
            catalog, schema, table, column = key
            from trino_tpu.connectors.api import TableHandle

            self.lookup(TableHandle(catalog, schema, table), column)
            with self._lock:
                ent = self._entries.get((key, version))
            if ent is not None:
                return ent
        raise KeyError(f"no global dictionary entry {key} v{version}")

    def resolve(self, key, version: int) -> StringDictionary:
        """Dictionary for a shipped (key, version) ref.  Tries the local
        registry, then connector re-registration (generated catalogs are
        deterministic, so the re-derived version matches), then the fetch
        hook; an unresolvable ref RAISES — decoding through a wrong
        dictionary would be silently wrong results."""
        key = tuple(key)
        try:
            return self.entry(key, version).dictionary
        except KeyError:
            pass
        if self.fetch_hook is not None:
            d = self.fetch_hook(key, version)
            if d is not None:
                catalog, schema, table, column = key
                ent = DictionaryEntry(
                    key, version, d, False, dictionary_fingerprint(d)
                )
                with self._lock:
                    self._entries.setdefault((key, version), ent)
                    self._latest[key] = max(self._latest.get(key, 0), version)
                    self._by_fp.setdefault(ent.fingerprint, ent.ref)
                return d
        raise KeyError(
            f"unresolvable global dictionary ref {key} v{version} "
            "(no local entry, connector, or fetch hook)"
        )

    # -- snapshots -------------------------------------------------------------

    def snapshot_doc(self, max_inline: int = DEFAULT_MAX_INLINE_VALUES) -> dict:
        """JSON-able snapshot of every (key, version).  Values inline up to
        `max_inline`; larger and pattern-backed dictionaries snapshot as
        metadata only — a re-registering connector adopts the recorded
        version so pre-restart refs stay valid."""
        entries = []
        with self._lock:
            items = sorted(self._entries.items())
        for (key, version), ent in items:
            rec = {
                "key": list(key),
                "version": version,
                "unique": ent.unique,
                "fingerprint": repr(ent.fingerprint),
                "len": len(ent.dictionary.values),
                "remap": ent.remap,
                "values": None,
            }
            d = ent.dictionary
            if (
                not isinstance(d, PatternDictionary)
                and len(d.values) <= max_inline
            ):
                rec["values"] = list(d.values)
                rec["ordered"] = not isinstance(d, UnorderedDictionary)
            entries.append(rec)
        return {"version": SNAPSHOT_VERSION, "entries": entries}

    def load_doc(self, doc) -> int:
        """Adopt a snapshot document (tolerant — see load_snapshot).
        Returns the number of entries restored or marked for adoption."""
        if not doc:
            return 0
        n = 0
        for rec in doc.get("entries") or ():
            try:
                key = tuple(rec["key"])
                version = int(rec["version"])
                unique = bool(rec.get("unique"))
                values = rec.get("values")
            except (KeyError, TypeError, ValueError):
                log.warning("global dictionary snapshot entry ignored: %r", rec)
                continue
            with self._lock:
                if values is not None:
                    if (key, version) in self._entries:
                        n += 1
                        continue
                    cls = (
                        StringDictionary if rec.get("ordered", True)
                        else UnorderedDictionary
                    )
                    try:
                        d = cls(values)
                    except AssertionError:
                        log.warning(
                            "global dictionary snapshot entry for %s v%d is "
                            "not sorted-unique; ignored", key, version,
                        )
                        continue
                    ent = DictionaryEntry(
                        key, version, d, unique, dictionary_fingerprint(d),
                        bool(rec.get("remap")),
                    )
                    self._entries[(key, version)] = ent
                    self._latest[key] = max(self._latest.get(key, 0), version)
                    self._by_fp[ent.fingerprint] = ent.ref
                else:
                    fp = rec.get("fingerprint")
                    if fp:
                        self._adopt.setdefault(key, {})[fp] = (version, unique)
            n += 1
        return n

    def save_snapshot(self, location: str,
                      max_inline: int = DEFAULT_MAX_INLINE_VALUES) -> None:
        """Persist atomically through the filesystem SPI (tmp + rename —
        a reader never observes a torn snapshot)."""
        from trino_tpu.filesystem import filesystem_for, strip_scheme

        fs = filesystem_for(location)
        doc = self.snapshot_doc(max_inline)
        fs.write(
            strip_scheme(location),
            (json.dumps(doc, indent=1) + "\n").encode(),
        )

    def load_snapshot(self, location: str) -> int:
        """Load a snapshot; a missing/torn/unreadable one degrades LOUDLY
        to producer-local codes (plans lose varchar co-location — slower,
        never wrong).  Returns entries adopted (0 on degrade)."""
        from trino_tpu.filesystem import filesystem_for, strip_scheme

        try:
            fs = filesystem_for(location)
            path = strip_scheme(location)
            if not fs.exists(path):
                log.warning(
                    "global dictionary snapshot missing at %s: degrading to "
                    "producer-local codes (varchar keys lose co-location "
                    "until connectors re-register)", location,
                )
                return 0
            doc = json.loads(fs.read(path).decode())
        except (NotImplementedError, OSError, ValueError) as e:
            log.warning(
                "global dictionary snapshot unreadable at %s (%s): degrading "
                "to producer-local codes (never wrong results, but varchar "
                "keys repartition until connectors re-register)", location, e,
            )
            return 0
        return self.load_doc(doc)

    # -- maintenance -----------------------------------------------------------

    def reset(self) -> None:
        """Drop every assignment (tests)."""
        with self._lock:
            self._entries.clear()
            self._latest.clear()
            self._by_fp.clear()
            self._adopt.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "keys": len(self._latest),
                "versions": len(self._entries),
                "unique": sum(
                    1 for e in self._entries.values() if e.unique
                ),
            }


#: the process singleton (coordinator-authoritative; workers are replicas)
DICTIONARY_SERVICE = GlobalDictionaryService()


def coordinator_fetch_hook(base_url: str):
    """fetch_hook resolving refs from a coordinator's
    GET /v1/dictionary/{catalog}/{schema}/{table}/{column}?version=N."""
    import urllib.request

    def fetch(key, version):
        catalog, schema, table, column = key
        url = (
            f"{base_url.rstrip('/')}/v1/dictionary/{catalog}/{schema}/"
            f"{table}/{column}?version={int(version)}"
        )
        try:
            with urllib.request.urlopen(url, timeout=30) as r:
                doc = json.loads(r.read().decode())
        except (OSError, ValueError) as e:
            log.warning("dictionary fetch failed for %s v%s: %s",
                        key, version, e)
            return None
        values = doc.get("values")
        if values is None or int(doc.get("version", -1)) != int(version):
            return None
        cls = StringDictionary if doc.get("ordered", True) else UnorderedDictionary
        return cls(values)

    return fetch
