"""Configuration file loading.

Reference roles: the launcher's etc/ directory layout —
`etc/config.properties` (node/coordinator config read by
io.airlift.configuration), `etc/catalog/<name>.properties` (one catalog per
file, `connector.name=` selects the plugin; server/CatalogManager loading),
and pointer files for password authentication / access control / resource
groups.

The properties syntax is the java.util.Properties subset the reference uses:
`key=value` or `key: value`, `#`/`!` comments, trailing-backslash line
continuation.
"""

from __future__ import annotations

import os
from typing import Optional


def load_properties(path: str) -> dict:
    """Parse one .properties file into {key: value} (strings)."""
    out: dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as fh:
        pending = ""
        for raw in fh:
            line = pending + raw.strip()
            pending = ""
            if not line or line[0] in "#!":
                continue
            if line.endswith("\\"):
                pending = line[:-1]
                continue
            for sep in ("=", ":"):
                if sep in line:
                    k, v = line.split(sep, 1)
                    out[k.strip()] = v.strip()
                    break
            else:
                out[line] = ""
    return out


#: connector.name -> factory(properties dict) -> Connector
#: (reference: spi ConnectorFactory registration via Plugin.getConnectorFactories)
def _factories() -> dict:
    from trino_tpu.connectors.blackhole import BlackholeConnector
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.tpch import TpchConnector

    reg = {
        "tpch": lambda p: TpchConnector(),
        "memory": lambda p: MemoryConnector(),
        "blackhole": lambda p: BlackholeConnector(),
    }
    try:
        from trino_tpu.connectors.tpcds import TpcdsConnector

        reg["tpcds"] = lambda p: TpcdsConnector()
    except ImportError:  # pragma: no cover
        pass

    def hive(p):
        from trino_tpu.connectors.hive import HiveConnector

        return HiveConnector(p["hive.metastore.catalog.dir"])

    def iceberg(p):
        from trino_tpu.connectors.iceberg import IcebergConnector

        return IcebergConnector(p["iceberg.catalog.warehouse"])

    def parquet(p):
        from trino_tpu.connectors.parquet import ParquetConnector

        return ParquetConnector(p["parquet.dir"])

    reg["hive"] = hive
    reg["iceberg"] = iceberg
    reg["parquet"] = parquet
    return reg


class EtcConfig:
    """Everything loaded from an etc/ directory."""

    def __init__(self, node_properties: dict, catalogs, session_defaults: dict,
                 cluster=None):
        self.node_properties = node_properties
        self.catalogs = catalogs
        self.session_defaults = session_defaults
        #: the typed ClusterConfig (trino_tpu/config) parsed from the same
        #: config.properties — breaker/heartbeat/lifecycle/remote knobs
        self.cluster = cluster


def load_etc(etc_dir: str, install: bool = True) -> EtcConfig:
    """Load config.properties + etc/catalog/*.properties into a CatalogManager
    and node/session settings (reference: the server launcher's config
    loading + CatalogStore).  The same properties feed the TYPED config
    system (trino_tpu/config): breaker/heartbeat/lifecycle/remote/worker
    knobs, installed process-wide unless `install=False` — installation
    also applies the eager sections (memory pool limit; the persistent
    XLA compile cache from `compile-cache.dir`, which must be in effect
    before the first jit)."""
    from trino_tpu.connectors.api import CatalogManager

    node_props: dict = {}
    cfg = os.path.join(etc_dir, "config.properties")
    if os.path.exists(cfg):
        node_props = load_properties(cfg)
    from trino_tpu.config import install_config, load_cluster_config

    cluster = load_cluster_config(node_props)
    if install:
        install_config(cluster)
    cm = CatalogManager()
    factories = _factories()
    cat_dir = os.path.join(etc_dir, "catalog")
    if os.path.isdir(cat_dir):
        for fn in sorted(os.listdir(cat_dir)):
            if not fn.endswith(".properties"):
                continue
            name = fn[: -len(".properties")]
            props = load_properties(os.path.join(cat_dir, fn))
            conn_name = props.get("connector.name")
            if conn_name is None:
                raise ValueError(f"{fn}: missing connector.name")
            factory = factories.get(conn_name)
            if factory is None:
                raise ValueError(f"{fn}: unknown connector.name {conn_name!r}")
            cm.register(name, factory(props))
    # session property defaults: `session.<name>=value` entries
    session_defaults = {}
    for k, v in node_props.items():
        if k.startswith("session."):
            session_defaults[k[len("session."):]] = _coerce(v)
    return EtcConfig(node_props, cm, session_defaults, cluster=cluster)


def _coerce(v: str):
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        return v


def runner_from_etc(etc_dir: str, **kw):
    """LocalQueryRunner wired from an etc/ directory (catalogs, session
    defaults, optional access-control and password files)."""
    from trino_tpu.runtime.runner import LocalQueryRunner

    cfg = load_etc(etc_dir)
    catalog = cfg.node_properties.get("default.catalog", "tpch")
    schema = cfg.node_properties.get("default.schema", "tiny")
    if catalog not in cfg.catalogs.names():
        names = cfg.catalogs.names()
        if names:
            catalog = sorted(names)[0]
    r = LocalQueryRunner(
        catalog=catalog,
        schema=schema,
        catalogs=cfg.catalogs,
        **kw,
    )
    for k, v in cfg.session_defaults.items():
        try:
            r.properties.set(k, v)
        except Exception:
            pass
    # event-listener plugin loading (reference: etc/event-listener.properties
    # with event-listener.name=...)
    el_path = os.path.join(etc_dir, "event-listener.properties")
    if os.path.exists(el_path):
        el_props = load_properties(el_path)
        el_name = el_props.get("event-listener.name")
        if el_name != "file":
            raise ValueError(
                f"event-listener.properties: unknown event-listener.name "
                f"{el_name!r} (supported: 'file')"
            )
        if "file.path" not in el_props:
            raise ValueError("event-listener.properties: missing file.path")
        from trino_tpu.runtime.events import FileEventListener

        r.events.add(FileEventListener(el_props["file.path"]))
    # query performance observatory: the JSONL audit log (`audit.log-path`)
    # and the per-query profile archive (`profile.archive-dir`) attach when
    # configured — both no-ops without their knobs (the archive usually
    # attached already at runner construction, since load_etc installed the
    # typed config first; this covers pre-built configs too)
    from trino_tpu.telemetry.audit import attach_audit_log
    from trino_tpu.telemetry.profile_store import attach_profile_store

    attach_profile_store(r)
    attach_audit_log(r)
    # restart resilience: an etc/-driven runner gets its prewarm executor
    # (runtime/prewarm) when `prewarm.manifest-path` is configured — the
    # CoordinatorServer then replays it at start, and grow paths re-trace
    # at the new mesh signature (no-op without the knob)
    from trino_tpu.runtime.prewarm import attach_prewarm

    attach_prewarm(r)
    ac_file = cfg.node_properties.get("access-control.config-file")
    if ac_file:
        import json

        from trino_tpu.server.security import RuleBasedAccessControl

        with open(ac_file) as fh:
            doc = json.load(fh)
        r.access_control = RuleBasedAccessControl.from_dicts(
            doc.get("tables", doc.get("rules", []))
        )
    return r
