"""Hierarchical memory accounting.

Reference: lib/trino-memory-context (AggregatedMemoryContext.java — the
operator -> driver -> pipeline -> task -> pool reservation tree) +
memory/MemoryPool.java:44.  Device HBM is the scarce resource here; batches
report their device footprint (capacity x dtype width, masks included) and
blocking operators reserve before materializing.  Exceeding the pool raises
ExceededMemoryLimitException — the hook where partition-wave fallback (the
spill analog, SURVEY.md §5.7) takes over.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ExceededMemoryLimitException(RuntimeError):
    def __init__(self, message: str, node: Optional["MemoryContext"] = None):
        super().__init__(message)
        #: the tree node whose limit blocked the reservation (the pool root
        #: for cluster-wide pressure, a query context for per-query budgets)
        self.node = node


def batch_bytes(batch) -> int:
    """Device footprint of a Batch (columns + validity + row mask)."""
    total = 0
    for c in batch.columns:
        total += c.data.size * c.data.dtype.itemsize
        if c.valid is not None:
            total += c.valid.size
    if batch.row_mask is not None:
        total += np.asarray(batch.row_mask).size
    return int(total)


class MemoryContext:
    """One node in the reservation tree; reservations aggregate to the root
    pool (reference: AggregatedMemoryContext.newLocalMemoryContext)."""

    def __init__(self, parent: Optional["MemoryContext"] = None, name: str = "root",
                 limit_bytes: int = 0):
        self.parent = parent
        self.name = name
        self.limit_bytes = limit_bytes  # 0 = unlimited (checked at this node)
        self.reserved = 0
        self.peak = 0
        #: pool-root hook (reference: LowMemoryKiller): called as
        #: hook(blocked_node, requesting_ctx, delta) when a reservation
        #: exceeds this node's limit; True = something was freed, retry
        self.on_exceeded = None
        #: query roots registered on a pool root (killer victim candidates)
        self.query_children: list = []
        #: lifecycle QueryContext for query roots (killed victims abort
        #: through it at their next cooperative check)
        self.owner = None

    def child(self, name: str) -> "MemoryContext":
        return MemoryContext(self, name)

    def query_root(self) -> "MemoryContext":
        """The query-level ancestor of this node (self when directly under
        the pool root, or detached)."""
        node = self
        while node.parent is not None and node.parent.parent is not None:
            node = node.parent
        return node

    def set_bytes(self, n: int) -> None:
        delta = n - self.reserved
        self.add_bytes(delta)

    def add_bytes(self, delta: int) -> None:
        while True:
            try:
                return self._reserve(delta)
            except ExceededMemoryLimitException as e:
                # the low-memory-killer hook lives on the pool root; a
                # per-query budget (no hook) propagates to the requester,
                # which is the wave/spill fallback's signal
                hook = getattr(e.node, "on_exceeded", None)
                if (
                    hook is None
                    or delta <= 0
                    or not hook(e.node, self, delta)
                ):
                    raise

    def _reserve(self, delta: int) -> None:
        visited = []
        node = self
        try:
            while node is not None:
                node.reserved += delta
                visited.append(node)
                if node.limit_bytes and node.reserved > node.limit_bytes:
                    raise ExceededMemoryLimitException(
                        f"memory limit exceeded at {node.name}: "
                        f"{node.reserved} > {node.limit_bytes} bytes",
                        node=node,
                    )
                node.peak = max(node.peak, node.reserved)
                node = node.parent
        except ExceededMemoryLimitException:
            for v in visited:  # undo so accounting stays consistent
                v.reserved -= delta
            raise

    def close(self) -> None:
        self.add_bytes(-self.reserved)

    def force_release(self) -> None:
        """Reclaim this subtree's accounting without cooperating with its
        operators (the killer's reclaim + end-of-statement cleanup): the
        reservation is subtracted from every ancestor and the node DETACHES
        from the tree, so late operator close() calls from a dying query can
        no longer corrupt the shared pool."""
        root = self
        while root.parent is not None:
            root = root.parent
        if self in root.query_children:
            root.query_children.remove(self)
        node, delta = self.parent, -self.reserved
        while node is not None:
            node.reserved += delta
            node = node.parent
        self.reserved = 0
        self.parent = None


class MemoryPool:
    """Per-query (or per-process) pool root (reference: MemoryPool.java:44)."""

    def __init__(self, limit_bytes: int = 0):
        self.root = MemoryContext(None, "pool", limit_bytes)

    def query_context(self, query_id: str, limit_bytes: int = 0) -> MemoryContext:
        ctx = self.root.child(f"query:{query_id}")
        ctx.limit_bytes = limit_bytes
        self.root.query_children.append(ctx)
        return ctx
