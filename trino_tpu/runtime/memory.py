"""Hierarchical memory accounting.

Reference: lib/trino-memory-context (AggregatedMemoryContext.java — the
operator -> driver -> pipeline -> task -> pool reservation tree) +
memory/MemoryPool.java:44.  Device HBM is the scarce resource here; batches
report their device footprint (capacity x dtype width, masks included) and
blocking operators reserve before materializing.  Exceeding the pool raises
ExceededMemoryLimitException — the hook where partition-wave fallback (the
spill analog, SURVEY.md §5.7) takes over.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ExceededMemoryLimitException(RuntimeError):
    pass


def batch_bytes(batch) -> int:
    """Device footprint of a Batch (columns + validity + row mask)."""
    total = 0
    for c in batch.columns:
        total += c.data.size * c.data.dtype.itemsize
        if c.valid is not None:
            total += c.valid.size
    if batch.row_mask is not None:
        total += np.asarray(batch.row_mask).size
    return int(total)


class MemoryContext:
    """One node in the reservation tree; reservations aggregate to the root
    pool (reference: AggregatedMemoryContext.newLocalMemoryContext)."""

    def __init__(self, parent: Optional["MemoryContext"] = None, name: str = "root",
                 limit_bytes: int = 0):
        self.parent = parent
        self.name = name
        self.limit_bytes = limit_bytes  # 0 = unlimited (checked at this node)
        self.reserved = 0
        self.peak = 0

    def child(self, name: str) -> "MemoryContext":
        return MemoryContext(self, name)

    def set_bytes(self, n: int) -> None:
        delta = n - self.reserved
        self.add_bytes(delta)

    def add_bytes(self, delta: int) -> None:
        visited = []
        node = self
        try:
            while node is not None:
                node.reserved += delta
                visited.append(node)
                if node.limit_bytes and node.reserved > node.limit_bytes:
                    raise ExceededMemoryLimitException(
                        f"memory limit exceeded at {node.name}: "
                        f"{node.reserved} > {node.limit_bytes} bytes"
                    )
                node.peak = max(node.peak, node.reserved)
                node = node.parent
        except ExceededMemoryLimitException:
            for v in visited:  # undo so accounting stays consistent
                v.reserved -= delta
            raise

    def close(self) -> None:
        self.add_bytes(-self.reserved)


class MemoryPool:
    """Per-query (or per-process) pool root (reference: MemoryPool.java:44)."""

    def __init__(self, limit_bytes: int = 0):
        self.root = MemoryContext(None, "pool", limit_bytes)

    def query_context(self, query_id: str, limit_bytes: int = 0) -> MemoryContext:
        ctx = self.root.child(f"query:{query_id}")
        ctx.limit_bytes = limit_bytes
        return ctx
