"""Hierarchical memory accounting.

Reference: lib/trino-memory-context (AggregatedMemoryContext.java — the
operator -> driver -> pipeline -> task -> pool reservation tree) +
memory/MemoryPool.java:44.  Device HBM is the scarce resource here; batches
report their device footprint (capacity x dtype width, masks included) and
blocking operators reserve before materializing.  Exceeding the pool raises
ExceededMemoryLimitException — the hook where partition-wave fallback (the
spill analog, SURVEY.md §5.7, runtime/spill.py) takes over.

Thread safety: the tree shares ONE reentrant lock per root (children adopt
their parent's lock at construction), because a reservation mutates every
ancestor counter on the way up — two queries reserving on the shared
process pool concurrently would otherwise corrupt accounting or double-trip
the limit.  The `on_exceeded` hook (revoke tier + low-memory killer,
runtime/lifecycle + runtime/spill) is deliberately invoked OUTSIDE the
lock: revocation spills through operator code that takes its own locks and
re-enters the tree to release.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


class ExceededMemoryLimitException(RuntimeError):
    def __init__(self, message: str, node: Optional["MemoryContext"] = None):
        super().__init__(message)
        #: the tree node whose limit blocked the reservation (the pool root
        #: for cluster-wide pressure, a query context for per-query budgets)
        self.node = node


def dictionary_bytes(d) -> int:
    """Device-adjacent footprint of one dictionary: the i32 code-indexed
    lookup tables string kernels gather through, one validity byte per
    entry, plus the encoded value bytes staged for result rendering.
    PatternDictionary values are lazy (potentially huge); they account the
    fixed per-entry overhead without forcing materialization."""
    n = len(d)
    overhead = n * 4 + n  # i32 table + validity plane
    if not isinstance(d.values, tuple):
        # PatternDictionary: values are lazy and potentially huge — account
        # the fixed per-entry overhead without forcing them
        return overhead
    return overhead + sum(len(v) for v in d.values)


def batch_bytes(batch, _seen_dicts: "set | None" = None) -> int:
    """Device footprint of a Batch (columns + validity + row mask + the
    dictionaries its coded columns reference, each distinct dictionary
    counted once).  `_seen_dicts` lets `batches_bytes` dedupe shared
    dictionaries ACROSS a batch list."""
    total = 0
    seen_dicts = set() if _seen_dicts is None else _seen_dicts
    for c in batch.columns:
        total += c.data.size * c.data.dtype.itemsize
        if c.valid is not None:
            total += c.valid.size
        d = getattr(c, "dictionary", None)
        if d is not None and id(d) not in seen_dicts:
            seen_dicts.add(id(d))
            total += _cached_dictionary_bytes(d)
    if batch.row_mask is not None:
        total += np.asarray(batch.row_mask).size
    return int(total)


def batches_bytes(batches) -> int:
    """Footprint of a batch LIST with shared dictionaries counted once —
    accumulating operators (sort runs, agg states, join builds) must sum
    through this, or a dictionary shared by every scan batch would be
    multiplied by the batch count and spuriously trip the budget."""
    seen: set = set()
    return sum(batch_bytes(b, _seen_dicts=seen) for b in batches)


def _cached_dictionary_bytes(d) -> int:
    """Dictionary footprints are O(|dict|) walks over value strings;
    memoize ON the (immutable) dictionary object itself — an id()-keyed
    side table would go stale when CPython recycles a dead dictionary's
    address for a new one."""
    v = getattr(d, "_nbytes", None)
    if v is None:
        v = dictionary_bytes(d)
        try:
            # StringDictionary is frozen; write through the same escape
            # hatch its own lazy _hash uses
            object.__setattr__(d, "_nbytes", v)
        except AttributeError:  # no slot (foreign dict type): recompute
            pass
    return v


class MemoryContext:
    """One node in the reservation tree; reservations aggregate to the root
    pool (reference: AggregatedMemoryContext.newLocalMemoryContext).  The
    whole tree is guarded by its root's reentrant lock."""

    def __init__(self, parent: Optional["MemoryContext"] = None, name: str = "root",
                 limit_bytes: int = 0):
        self.parent = parent
        self.name = name
        self.limit_bytes = limit_bytes  # 0 = unlimited (checked at this node)
        self.reserved = 0
        self.peak = 0
        #: pool-root hook (reference: LowMemoryKiller): called as
        #: hook(blocked_node, requesting_ctx, delta) when a reservation
        #: exceeds this node's limit; True = something was freed, retry
        self.on_exceeded = None
        #: query roots registered on a pool root (killer victim candidates)
        self.query_children: list = []
        #: True for per-query root nodes (set by MemoryPool.query_context /
        #: lifecycle.query_memory_context): with resource-group sub-pools
        #: between the pool root and the query layer, depth no longer
        #: identifies the query node — the flag does
        self.is_query_root = False
        #: lifecycle QueryContext for query roots (killed victims abort
        #: through it at their next cooperative check)
        self.owner = None
        #: ONE lock per tree, shared down from the root: reservations climb
        #: ancestors, so per-node locks would deadlock or interleave
        self._lock = parent._lock if parent is not None else threading.RLock()

    def child(self, name: str) -> "MemoryContext":
        return MemoryContext(self, name)

    def query_root(self) -> "MemoryContext":
        """The query-level ancestor of this node: the nearest ancestor
        (or self) flagged `is_query_root`, falling back to the old
        depth-based rule (self when directly under the pool root, or
        detached) for trees built without the flag."""
        with self._lock:
            node = self
            while node is not None:
                if node.is_query_root:
                    return node
                node = node.parent
            node = self
            while node.parent is not None and node.parent.parent is not None:
                node = node.parent
            return node

    def set_bytes(self, n: int) -> None:
        """Set this node's reservation to exactly `n`.  The read-modify-
        write runs UNDER the tree lock (the RLock makes the nested
        `_reserve` climb reentrant) — computing the delta outside would
        let a concurrent set_bytes on the same context (the revoke tier
        zeroing an operator the owner is still accounting) interleave and
        corrupt ancestors with a stale delta.  The escalation hook is
        still invoked outside the lock, and the retry recomputes the
        delta fresh."""
        while True:
            delta = 0
            try:
                with self._lock:
                    delta = n - self.reserved
                    return self._reserve(delta)
            except ExceededMemoryLimitException as e:
                hook = getattr(e.node, "on_exceeded", None)
                if hook is None or delta <= 0 or not hook(e.node, self, delta):
                    raise

    def close(self) -> None:
        self.set_bytes(0)

    def add_bytes(self, delta: int) -> None:
        while True:
            try:
                return self._reserve(delta)
            except ExceededMemoryLimitException as e:
                # the escalation hook (revoke tier, then the low-memory
                # killer) lives on the pool root; a per-query budget (no
                # hook) propagates to the requester, which is the
                # wave/spill fallback's signal.  Called OUTSIDE the tree
                # lock: revocation runs operator spill code.
                hook = getattr(e.node, "on_exceeded", None)
                if (
                    hook is None
                    or delta <= 0
                    or not hook(e.node, self, delta)
                ):
                    raise

    def _reserve(self, delta: int) -> None:
        with self._lock:
            visited = []
            node = self
            try:
                while node is not None:
                    node.reserved += delta
                    visited.append(node)
                    # releases (delta <= 0) NEVER fail: after a mid-query
                    # limit shrink the tree may sit above the new limit,
                    # and refusing to give memory back would wedge it there
                    if (
                        delta > 0
                        and node.limit_bytes
                        and node.reserved > node.limit_bytes
                    ):
                        raise ExceededMemoryLimitException(
                            f"memory limit exceeded at {node.name}: "
                            f"{node.reserved} > {node.limit_bytes} bytes",
                            node=node,
                        )
                    node.peak = max(node.peak, node.reserved)
                    node = node.parent
            except ExceededMemoryLimitException:
                for v in visited:  # undo so accounting stays consistent
                    v.reserved -= delta
                raise

    def force_release(self) -> None:
        """Reclaim this subtree's accounting without cooperating with its
        operators (the killer's reclaim + end-of-statement cleanup): the
        reservation is subtracted from every ancestor and the node DETACHES
        from the tree, so late operator close() calls from a dying query can
        no longer corrupt the shared pool."""
        with self._lock:
            node, delta = self.parent, -self.reserved
            while node is not None:
                # a query root may be registered on BOTH its resource
                # group's sub-pool and the shared pool root — deregister
                # from every ancestor so neither escalation tier can pick
                # a detached victim
                if self in node.query_children:
                    node.query_children.remove(self)
                node.reserved += delta
                node = node.parent
            self.reserved = 0
            self.parent = None


class MemoryPool:
    """Per-query (or per-process) pool root (reference: MemoryPool.java:44)."""

    def __init__(self, limit_bytes: int = 0):
        self.root = MemoryContext(None, "pool", limit_bytes)

    def query_context(self, query_id: str, limit_bytes: int = 0) -> MemoryContext:
        ctx = self.root.child(f"query:{query_id}")
        ctx.limit_bytes = limit_bytes
        ctx.is_query_root = True
        with self.root._lock:
            self.root.query_children.append(ctx)
        return ctx
