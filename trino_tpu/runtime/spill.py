"""Graceful degradation under memory pressure: HBM-budgeted partition
waves, filesystem-SPI spill, and memory revocation.

Reference: the Trino revoke+spill machinery SURVEY.md §5.7 maps onto an
HBM-budgeted k-pass partition loop —

  * ``HashBuilderOperator.startMemoryRevoke:372`` — a blocking operator
    asked to give memory back spills its state and releases its
    reservation (here: :class:`RevocableOperator` + :class:`MemoryEscalation`);
  * ``GenericPartitioningSpiller`` — state hash-partitions by the exchange
    row hash and persists through the spill SPI (here: :class:`SpillManager`
    over the FTE ``SpoolManager`` npz format and the filesystem SPI);
  * ``SpillingJoinProcessor`` — spilled join partitions process in
    sequential waves (here: :func:`partition_wave_join` and the mesh wave
    hooks in ``parallel/runner``).

The escalation ladder a reservation climbs (enforced by
tests/test_spill.py):

  1. **budget** — blocking operators (join build, hash aggregation,
     order-by sort, window) reserve their device footprint on the
     lifecycle memory pool BEFORE materializing;
  2. **revoke** — when the shared pool blocks, the largest *registered
     revocable* operator is asked to spill a partition and release its
     reservation (``trino_tpu_memory_revocations_total``);
  3. **wave** — an operator whose own reservation cannot fit degrades to
     ``k = next_pow2(need / budget)`` hash-partition waves, spilling
     non-resident partitions host-side (``trino_tpu_memory_waves_total``,
     ``trino_tpu_spill_bytes_total``);
  4. **kill** — the LowMemoryKiller remains the last resort, its
     largest-victim choice unchanged (``trino_tpu_memory_kills_total``).

Zero-cost-when-idle: none of this engages without a budget — the
compare_bench gate asserts every unconstrained warm benched query records
zero waves, zero spill, zero revocations.
"""

from __future__ import annotations

import math
import threading
import uuid
from typing import Callable, Optional

import numpy as np

#: partition-wave fan-out ceiling (a 64-pass query is already degraded far
#: past useful; beyond this the killer is the kinder answer)
MAX_WAVES = 64


# -- budget arithmetic ---------------------------------------------------------


def session_budget(properties) -> int:
    """Per-query session budget in bytes: the smallest nonzero of
    ``query_max_memory`` and the legacy ``query_max_memory_bytes``."""
    vals = []
    if properties is not None:
        for knob in ("query_max_memory", "query_max_memory_bytes"):
            try:
                v = int(properties.get(knob))
            except KeyError:  # pragma: no cover - older property sets
                v = 0
            if v > 0:
                vals.append(v)
    return min(vals) if vals else 0


def effective_budget(properties=None, memory_ctx=None) -> int:
    """The per-query device budget in bytes (0 = unconstrained): the
    smallest nonzero of the ``query_max_memory`` session property (or the
    legacy ``query_max_memory_bytes``), the query context's own limit, and
    any ancestor pool limit (``memory.pool-limit-bytes``)."""
    candidates = []
    sb = session_budget(properties)
    if sb > 0:
        candidates.append(sb)
    node = memory_ctx
    while node is not None:
        if node.limit_bytes:
            candidates.append(int(node.limit_bytes))
        node = node.parent
    return min(candidates) if candidates else 0


def wave_count(need: int, budget: int, properties=None) -> int:
    """``k = next_pow2(need / budget)`` partition-wave fan-out, clamped to
    [2, MAX_WAVES]; the ``memory_wave_partitions`` session property
    overrides (bisection knob)."""
    if properties is not None:
        try:
            k = int(properties.get("memory_wave_partitions"))
        except KeyError:  # pragma: no cover - older property sets
            k = 0
        if k > 0:
            return max(2, min(MAX_WAVES, k))
    if budget <= 0:
        return 2
    from trino_tpu.ops.common import next_pow2

    return max(
        2,
        min(MAX_WAVES, next_pow2(max(1, math.ceil(need / budget)), floor=2)),
    )


def spill_to_disk(properties) -> bool:
    """The ``spill_enabled`` session knob: False stages non-resident wave
    partitions in host RAM instead of the filesystem SPI (bisection)."""
    if properties is None:
        return True
    try:
        return bool(properties.get("spill_enabled"))
    except KeyError:  # pragma: no cover - older property sets
        return True


# -- observability -------------------------------------------------------------


class PressureObserver:
    """Routes wave/spill events to the metrics registry plus an optional
    per-query sink (a StatsCollector locally, a MeshProfile on the mesh —
    anything with ``bump(name, n)``), so EXPLAIN ANALYZE and Prometheus
    tell the same story."""

    def __init__(self, sink=None):
        self.sink = sink

    def waves(self, operator: str, k: int) -> None:
        from trino_tpu.telemetry.metrics import memory_waves_counter

        memory_waves_counter().labels(operator).inc(k)
        if self.sink is not None:
            self.sink.bump("memory_wave", k)

    def spilled(self, nbytes: int) -> None:
        from trino_tpu.telemetry.metrics import spill_bytes_counter

        spill_bytes_counter().inc(nbytes)
        if self.sink is not None:
            self.sink.bump("spill_bytes", nbytes)


# -- the partitioning spiller --------------------------------------------------


class SpillManager:
    """Partitioned host-side spill store (GenericPartitioningSpiller role):
    persists lists of host batches per (tag, partition) through the FTE
    ``SpoolManager`` npz format, which itself rides the filesystem SPI —
    pointing ``memory.spill-dir`` at an object store becomes a
    configuration change the day a remote filesystem lands."""

    def __init__(self, directory: Optional[str] = None, observer=None):
        from trino_tpu.runtime.fte import SpoolManager

        if directory is None:
            from trino_tpu.config import get_config

            directory = get_config().memory.spill_dir or None
        self.spool = SpoolManager(directory)
        #: unique per manager so shared spill dirs never collide
        self._prefix = f"spill_{uuid.uuid4().hex[:12]}"
        #: (tag, part) -> (symbols, dictionaries): the schema needed to
        #: rehydrate (npz stores arrays, not types)
        self._meta: dict = {}
        self._seq: dict = {}
        self.bytes_spilled = 0
        self._closed = False
        self.observer = observer if observer is not None else PressureObserver()
        # abort hygiene: a query killed or canceled mid-wave abandons its
        # wave generator, whose finally-close only runs at GC — register
        # with the owning query's lifecycle so the statement-end path
        # (runner.execute / worker task finally) deletes our partitions
        # through the filesystem SPI immediately
        from trino_tpu.runtime.lifecycle import register_spill

        register_spill(self)

    def _fid(self, tag: str, part: int) -> int:
        key = (tag, part)
        fid = self._seq.get(key)
        if fid is None:
            fid = len(self._seq)
            self._seq[key] = fid
        return fid

    def save(self, tag: str, part: int, batches: list) -> int:
        """Spill host batches as one partition; returns bytes written.
        Dictionaries are unified across the partition's batches first so
        ONE dictionary list rehydrates every batch exactly."""
        from trino_tpu.ops.sort import _unify_host_dictionaries
        from trino_tpu.planner import plan as P
        from trino_tpu.runtime.memory import batches_bytes

        if not batches:
            return 0
        batches = _unify_host_dictionaries(list(batches))
        first = batches[0]
        symbols = [
            P.Symbol(f"c{i}", c.type) for i, c in enumerate(first.columns)
        ]
        self.spool.save(self._prefix + "_" + tag, self._fid(tag, part),
                        batches, symbols)
        self._meta[(tag, part)] = (
            symbols, [c.dictionary for c in first.columns]
        )
        nbytes = batches_bytes(batches)
        self.bytes_spilled += nbytes
        self.observer.spilled(nbytes)
        return nbytes

    def load(self, tag: str, part: int) -> list:
        """Rehydrate one partition's host batches ([] when the partition
        was empty and never written)."""
        meta = self._meta.get((tag, part))
        if meta is None:
            return []
        symbols, dicts = meta
        out = self.spool.load(
            self._prefix + "_" + tag, self._fid(tag, part), symbols, dicts
        )
        return out if out is not None else []

    def close(self) -> None:
        # idempotent: the abort path (lifecycle.release_spills) and the
        # wave loop's own finally may both close — a double delete of a
        # tempdir-owned spool would raise on the second fs.list
        if self._closed:
            return
        self._closed = True
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None:
            ctx.unregister_spill(self)
        # a CONFIGURED spill dir is shared: the spool only removes
        # directories it created, and the orphan sweep is an hours-scale
        # backstop — delete our own partition files (we know every
        # (tag, part) we wrote) so sustained pressure cannot fill the disk
        for (tag, part), fid in list(self._seq.items()):
            if (tag, part) in self._meta:
                try:
                    self.spool.fs.delete(
                        self.spool._path(self._prefix + "_" + tag, fid)
                    )
                except OSError:  # pragma: no cover - already swept
                    pass
        self._meta.clear()
        self.spool.close()


class _DiskSide:
    """One operator input, hash-partitioned into k on-disk partitions."""

    def __init__(self, spiller: SpillManager, tag: str, n_parts: int):
        self.spiller = spiller
        self.tag = tag
        self.n_parts = n_parts

    def load_part(self, part: int) -> list:
        return self.spiller.load(self.tag, part)


class _RamSide:
    """spill_enabled=false fallback: partitions stay in host RAM."""

    def __init__(self, buckets: list):
        self.buckets = buckets
        self.n_parts = len(buckets)

    def load_part(self, part: int) -> list:
        return self.buckets[part]


def partition_side(host_batches: list, key_channels, k: int,
                   spiller: Optional[SpillManager], tag: str):
    """Hash-partition host batches by the exchange row hash (the
    value-stable host mirror, ``serde.stable_row_hash``) into k partitions;
    spilled to disk when a spiller is given, staged in RAM otherwise."""
    from trino_tpu.parallel.serde import partition_batches

    buckets = partition_batches(host_batches, list(key_channels), k)
    if spiller is None:
        return _RamSide(buckets)
    for part, bucket in enumerate(buckets):
        if bucket:
            spiller.save(tag, part, bucket)
        buckets[part] = None  # free RAM as partitions land on disk
    return _DiskSide(spiller, tag, k)


# -- partition-wave join (SpillingJoinProcessor role) --------------------------


def partition_wave_join(make_op, build_side, probe_side, n_waves: int,
                        ctx, observer: PressureObserver):
    """k-pass partition-wave join: each wave materializes only its slice of
    the build side on device while both sides re-feed from the spill tier.
    Partitioning both sides by the same key-value hash preserves exact
    results for inner/left/full joins — every potential match pair lands in
    the same wave, and each row is emitted by exactly one wave."""
    import jax

    from trino_tpu.runtime.memory import batches_bytes

    observer.waves("join", n_waves)
    for wave in range(n_waves):
        wave_build = [jax.device_put(b) for b in build_side.load_part(wave)]
        wave_bytes = batches_bytes(wave_build)
        if ctx is not None:
            # raw slice + compacted copy
            reserve_wave_working_set(ctx, 2 * wave_bytes)
        op = make_op()
        op.set_build(wave_build)
        del wave_build

        def probe_feed(w=wave):
            for hb in probe_side.load_part(w):
                yield jax.device_put(hb)

        yield from op.process(probe_feed())
        del op
    if ctx is not None:
        ctx.close()


def pull_host(*trees):
    """The spill tier's DECLARED host boundary: device values cross to
    host exactly here, immediately before being partitioned and spilled.
    Lives in runtime/ (not the linted device paths) because moving data
    off-device is this module's whole purpose."""
    from trino_tpu.columnar.batch import device_get_async

    out = device_get_async(tuple(trees))
    return out if len(out) > 1 else out[0]


def reserve_wave_working_set(ctx, nbytes: int) -> None:
    """Account one wave's working set on the reservation tree, BEST
    EFFORT: the wave path is already the degradation tier, so its own
    bookkeeping must never kill the query it is saving — when even a
    single wave cannot fit the (possibly further-shrunk) budget, the wave
    proceeds with the reservation pinned at whatever was admitted
    (reference analog: revocable memory is accounted outside the query
    limit in MemoryPool.getReservedRevocableBytes)."""
    from trino_tpu.runtime.memory import ExceededMemoryLimitException

    try:
        ctx.set_bytes(nbytes)
    except ExceededMemoryLimitException:
        pass


# -- memory revocation (startMemoryRevoke role) --------------------------------


class RevocableOperator:
    """A registered wave-capable blocking operator: when the shared pool
    blocks, the escalation hook asks the largest one to spill its state
    and release its reservation instead of shooting a query.

    The handle's lock serializes the revoker (another query's thread)
    against the owner: ``revoke()`` runs the spill callback under it, and
    the owner's ``revoked`` reads take it too — an owner that observes
    ``revoked == True`` is guaranteed the spill completed."""

    def __init__(self, operator: str, ctx, spill_fn: Callable[[], int]):
        self.operator = operator
        self.ctx = ctx
        self._spill_fn = spill_fn
        #: REENTRANT on purpose: owners guard their own state mutations
        #: with it too, and an owner-thread reservation that triggers the
        #: escalation hook may revoke its OWN handle (self-revocation —
        #: spill yourself before the killer shoots someone)
        self.lock = threading.RLock()
        self._revoked = False
        self._done = False

    @property
    def revoked(self) -> bool:
        with self.lock:
            return self._revoked

    def reserved_bytes(self) -> int:
        """Ranking key for victim choice (a point-in-time read)."""
        return int(self.ctx.reserved) if self.ctx is not None else 0

    def revoke(self) -> int:
        """Spill + release; returns bytes freed (0 when already revoked or
        finished — the registry then tries the next candidate)."""
        with self.lock:
            if self._revoked or self._done:
                return 0
            freed = int(self._spill_fn() or 0)
            self._revoked = True
        REVOCABLES.unregister(self)
        return freed

    def finish(self) -> None:
        """Owner completed (normally or not): no longer revocable."""
        with self.lock:
            self._done = True
        REVOCABLES.unregister(self)


class RevocableRegistry:
    """Process-wide registry the escalation hook consults (reference role:
    the ClusterMemoryManager's taskMemoryRevoking candidates)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: list = []

    def register(self, handle: RevocableOperator) -> RevocableOperator:
        with self._lock:
            self._entries.append(handle)
        return handle

    def unregister(self, handle) -> None:
        with self._lock:
            if handle in self._entries:
                self._entries.remove(handle)

    def live(self) -> list:
        with self._lock:
            return list(self._entries)

    def revoke_largest(self) -> int:
        """Ask the largest-reservation revocable to spill; falls through to
        smaller ones if the largest races to completion first.  Returns
        bytes freed (0 = nothing revocable)."""
        for h in sorted(
            self.live(), key=lambda e: e.reserved_bytes(), reverse=True
        ):
            freed = h.revoke()
            if freed > 0:
                return freed
        return 0


#: the process registry (cleared by tests via REVOCABLES._entries checks)
REVOCABLES = RevocableRegistry()


class MemoryEscalation:
    """Pool-root ``on_exceeded`` hook: the revoke tier runs BEFORE the
    low-memory killer — spilling a cooperative operator is strictly kinder
    than shooting a query, and the killer's largest-victim semantics are
    unchanged when revocation cannot free the shortfall."""

    def __init__(self, killer=None):
        if killer is None:
            from trino_tpu.runtime.lifecycle import LowMemoryKiller

            killer = LowMemoryKiller()
        self.killer = killer

    def __call__(self, pool_root, requesting, delta: int) -> bool:
        freed = REVOCABLES.revoke_largest()
        if freed > 0:
            from trino_tpu.telemetry.metrics import (
                memory_revocations_counter,
            )

            memory_revocations_counter().inc()
            return True  # something released: retry the reservation
        return self.killer(pool_root, requesting, delta)


# -- host-side wave slicing (shared by agg/window waves) -----------------------


def host_wave_slice(hb, key_channels: list, n_waves: int, wave: int):
    """Rows of a HOST batch whose key VALUE hash lands in `wave`, compacted
    to a dense host batch (None when empty).  Value hashing (not code
    hashing) keeps groups whole across batches with batch-local
    dictionaries."""
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.parallel.serde import stable_row_hash

    h = stable_row_hash(hb, key_channels)
    keep = np.asarray(hb.mask()) & ((h % np.uint64(n_waves)) == np.uint64(wave))
    n = int(keep.sum())
    if n == 0:
        return None
    idx = np.nonzero(keep)[0]
    cols = []
    for c in hb.columns:
        cols.append(
            Column(
                np.asarray(c.data)[idx],
                c.type,
                None if c.valid is None else np.asarray(c.valid)[idx],
                c.dictionary,
                None if c.lengths is None else np.asarray(c.lengths)[idx],
            )
        )
    return Batch(cols, np.ones(n, dtype=bool))


class SpillingAccumulator:
    """Bounded accumulation of host batches with an optional disk tier:
    chunks pushed over the course of a stream land in RAM or (spiller
    given) the filesystem SPI, and are re-read chunk-at-a-time per wave.
    The shared shape under the agg-state / window / raw-input wave
    streams."""

    def __init__(self, spiller: Optional[SpillManager], tag: str):
        self.spiller = spiller
        self.tag = tag
        self._chunks: list = []  # part index (disk) or [host batches] (ram)
        self.total_bytes = 0

    def push_chunk(self, host_batches: list) -> None:
        from trino_tpu.runtime.memory import batches_bytes

        if not host_batches:
            return
        self.total_bytes += batches_bytes(host_batches)
        if self.spiller is not None:
            part = len(self._chunks)
            self.spiller.save(self.tag, part, list(host_batches))
            self._chunks.append(part)
        else:
            self._chunks.append(list(host_batches))

    def __len__(self) -> int:
        return len(self._chunks)

    def chunks(self):
        """Iterate chunk-at-a-time (one chunk resident in RAM when disk-
        backed): yields lists of host batches."""
        for c in self._chunks:
            if isinstance(c, int):
                yield self.spiller.load(self.tag, c)
            else:
                yield c

    def wave_parts(self, key_channels: list, n_waves: int, wave: int) -> list:
        """Every chunk's slice for one wave (host batches).

        Disk-backed chunks are re-read once PER WAVE (k x total read
        amplification).  Deliberate for the state-wave consumers: k is
        only known after the last chunk lands, and agg/window states are
        compacted partials, typically orders of magnitude smaller than
        the raw input.  The join paths — where the spilled data IS the
        raw input — partition at write time instead (partition_side) and
        read each wave exactly once."""
        parts = []
        for chunk in self.chunks():
            for hb in chunk:
                p = host_wave_slice(hb, key_channels, n_waves, wave)
                if p is not None:
                    parts.append(p)
        return parts
