"""Driver: composes a source + operator chain into a batch stream.

Reference role: operator/Driver.java:371 (processInternal) — but where the
reference pulls pages operator-by-operator under a time-sliced executor, here
each operator is a generator transform and every device step is an async XLA
dispatch; the host thread just keeps the feed full (SURVEY.md §7 maps
TaskExecutor time-slicing to a host feed/step/drain pipeline).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Sequence

from trino_tpu.columnar import Batch


class Driver:
    def __init__(self, source: Iterable[Batch], operators: Sequence = ()):
        self.source = source
        self.operators = list(operators)

    def run(self) -> Iterator[Batch]:
        from trino_tpu.runtime.lifecycle import check_current

        stream: Iterable[Batch] = self.source
        for op in self.operators:
            stream = op.process(stream)

        def guarded(s: Iterable[Batch]) -> Iterator[Batch]:
            # cooperative cancellation per batch: a canceled/expired query
            # aborts between pages instead of draining the whole chain
            for b in s:
                check_current()
                yield b

        return guarded(stream)

    def collect(self) -> list[Batch]:
        return list(self.run())

    def rows(self) -> list[list]:
        out = []
        for b in self.collect():
            out.extend(b.to_pylist())
        return out
