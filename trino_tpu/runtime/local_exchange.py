"""Local (intra-task) exchange: N producer threads feeding one consumer.

Reference role: operator/exchange/LocalExchange.java + the `task_concurrency`
session property — the reference splits a task's pipeline into parallel
drivers connected by an in-memory exchange.  Here the device pipeline is one
XLA stream (the compiler owns that parallelism), so the concurrency that
matters is HOST-side: split reading, page decoding and host->device feeding.
This exchange runs those producers on a thread pool with a bounded buffer
(backpressure), preserving no particular order (like the reference's
arbitrary-distribution local exchange).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Sequence


_DONE = object()


def parallel_feed(
    makers: Sequence[Callable[[], Iterable]],
    workers: int,
    buffer: int = 8,
) -> Iterator:
    """Drain `makers` (thunks returning iterables) concurrently on `workers`
    threads; yield items as they arrive.

    A producer exception is re-raised at the consumer promptly (in-flight
    items after a failure are dropped, not yielded).  If the CONSUMER
    abandons the generator (LIMIT, downstream error), the finally block
    stops the producers and drains the queue so no thread stays blocked on a
    full buffer pinning device batches."""
    if workers <= 1 or len(makers) <= 1:
        for mk in makers:
            yield from mk()
        return
    q: "queue.Queue" = queue.Queue(maxsize=max(buffer, workers))
    pending = list(enumerate(makers))
    lock = threading.Lock()
    stop = threading.Event()
    n_workers = min(workers, len(makers))
    errors: list = []

    def worker():
        while not stop.is_set():
            with lock:
                if errors or not pending:
                    break
                _, mk = pending.pop(0)
            try:
                for item in mk():
                    if stop.is_set() or errors:
                        break
                    q.put(item)
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                with lock:
                    errors.append(e)
                break
        q.put(_DONE)

    threads = [
        threading.Thread(
            target=worker, daemon=True, name=f"local-exchange-{i}"
        )
        for i in range(n_workers)
    ]
    for t in threads:
        t.start()
    try:
        done = 0
        while done < n_workers:
            item = q.get()
            if item is _DONE:
                done += 1
                continue
            with lock:
                failed = bool(errors)
            if failed:
                continue  # drop in-flight items after a failure
            yield item
        if errors:
            raise errors[0]
    finally:
        stop.set()
        # unblock any producer waiting on a full queue
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
