"""Resource groups: admission control and fair queuing.

Reference: execution/resourcegroups/InternalResourceGroup.java +
InternalResourceGroupManager.java — queries are admitted into a tree of
groups, each with hard concurrency and queue limits; queued queries start
as running ones finish.

Engine mapping: the scarce resource is the device, so `hard_concurrency`
bounds concurrent engine executions per group and `max_queued` bounds the
backlog.  A selector picks the group by user/source (the resource-group
manager plugin's role, reduced to prefix rules).

PR 13 extensions toward the airlift analog:

  * ``weight`` — the group's share under the dispatcher's weighted-fair
    scheduler (runtime/dispatcher.QueryDispatcher picks the next eligible
    group by weighted virtual time, not FIFO across groups);
  * ``memory_limit_bytes`` — a per-group sub-pool of the PR 12 shared
    MemoryContext tree (`ResourceGroup.memory_context`): queries admitted
    through the group reserve under the group node, so a group at its
    limit degrades through the revoke -> wave -> kill ladder WITHIN the
    group (`GroupMemoryEscalation`) and can never kill a bystander
    group's query;
  * a properties-file format (`ResourceGroupManager.from_properties`):
    ``resource-groups.<name>.max-concurrency|max-queued|weight|
    memory-limit-bytes`` plus ``resource-groups.user.<user>=<name>``
    selector rules (the resource-group configuration manager's role).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

#: the dispatcher-owned group prewarm replays admit through (weight-capped
#: so a post-grow replay cannot starve live user queries — PR 8's replay
#: previously held the engine lock outright)
SYSTEM_PREWARM_GROUP = "system.prewarm"


class QueryQueueFullError(RuntimeError):
    """Reference: QUERY_QUEUE_FULL error code."""


@dataclass
class ResourceGroupConfig:
    name: str
    hard_concurrency: int = 1
    max_queued: int = 100
    #: weighted-fair share under the dispatcher (admissions of a saturated
    #: group pair with weights w1:w2 converge to the w1:w2 ratio)
    weight: int = 1
    #: per-group memory sub-pool limit (0 = no group limit): wired as a
    #: child of the PR 12 shared pool root by ResourceGroup.memory_context
    memory_limit_bytes: int = 0


class ResourceGroup:
    def __init__(self, config: ResourceGroupConfig):
        self.config = config
        self.running = 0
        self.queued: deque = deque()
        self.lock = threading.Lock()
        #: peak/telemetry counters (system.runtime-style observability)
        self.total_admitted = 0
        self.total_queued = 0
        #: the group's memory sub-pool (memory_context()); binding is
        #: created once and immutable after — readers need no lock
        self._memory = None
        #: dispatcher hook: called (outside the group lock) whenever a
        #: slot may have freed, so a LEGACY release() also wakes tickets
        #: waiting in the dispatcher's weighted-fair queue — without it a
        #: dispatcher ticket queued behind a dbapi-held slot would wait
        #: until some unrelated dispatcher event happened to fire
        self.on_slot_freed = None

    def memory_context(self, pool_root):
        """The group's sub-pool node under the shared pool root (created
        once, on first use): queries admitted through this group reserve
        under it, so `memory_limit_bytes` bounds the GROUP's total and a
        breach escalates within the group only (GroupMemoryEscalation).
        Returns None when the group declares no memory limit — unlimited
        groups reserve directly on the pool root, exactly as before."""
        if not self.config.memory_limit_bytes:
            return None
        with self.lock:
            if self._memory is None:
                ctx = pool_root.child(f"group:{self.config.name}")
                ctx.limit_bytes = int(self.config.memory_limit_bytes)
                ctx.on_exceeded = GroupMemoryEscalation(self.config.name)
                self._memory = ctx
            return self._memory

    def try_acquire_now(self) -> bool:
        """Non-blocking admission (the dispatcher's slot grab): True when a
        concurrency slot was taken.  Shares the `running` counter with the
        blocking acquire() path, so legacy holders (dbapi, direct tests)
        and dispatcher admissions see one consistent limit."""
        with self.lock:
            if self.running < self.config.hard_concurrency:
                self.running += 1
                self.total_admitted += 1
                return True
            return False

    def has_slot(self) -> bool:
        with self.lock:
            return self.running < self.config.hard_concurrency

    def acquire(self, timeout: Optional[float] = None) -> None:
        """Block until admitted; raise QueryQueueFullError when the queue
        is at max_queued (reference: InternalResourceGroup.run).  A timeout
        ALWAYS raises TimeoutError: when the wait expires but release() has
        already signaled our gate (the timeout/grant race), the granted slot
        is handed to the next waiter under the lock instead of being
        swallowed by a caller that has given up — a leaked slot there
        permanently shrinks the group's effective concurrency."""
        gate = None
        with self.lock:
            if self.running < self.config.hard_concurrency:
                self.running += 1
                self.total_admitted += 1
                return
            if len(self.queued) >= self.config.max_queued:
                raise QueryQueueFullError(
                    f"resource group {self.config.name} queue is full "
                    f"({self.config.max_queued})"
                )
            gate = self._make_gate()
            self.queued.append(gate)
            self.total_queued += 1
        if not gate.wait(timeout=timeout):
            with self.lock:
                try:
                    self.queued.remove(gate)
                except ValueError:
                    # raced with release(): the slot was granted to us after
                    # we timed out — pass it on, we are no longer waiting
                    self.total_admitted -= 1  # the grant never ran
                    self._hand_off_locked()
            self._notify_slot_freed()
            raise TimeoutError(
                f"queued in resource group {self.config.name} past timeout"
            )

    def _make_gate(self) -> threading.Event:
        """Seam for the timeout/grant race regression test (a gate whose
        wait() deterministically 'times out' after release() signals it)."""
        return threading.Event()

    def _hand_off_locked(self) -> None:  # lint: allow(unguarded-state)
        """Transfer one held slot onward (caller holds self.lock): wake the
        next waiter, or return the slot to the pool when nobody waits."""
        if self.queued:
            gate = self.queued.popleft()
            self.total_admitted += 1
            gate.set()
        else:
            self.running = max(0, self.running - 1)

    def release(self) -> None:
        with self.lock:
            self._hand_off_locked()
        self._notify_slot_freed()

    def _notify_slot_freed(self) -> None:
        """Run the dispatcher's scheduling kick (if attached) OUTSIDE the
        group lock — the dispatcher takes its own lock first, then this
        group's, and inverting that order here would be a deadlock."""
        cb = self.on_slot_freed
        if cb is not None:
            cb()

    def stats(self) -> dict:
        with self.lock:
            mem = self._memory
            return {
                "name": self.config.name,
                "running": self.running,
                "queued": len(self.queued),
                "hard_concurrency": self.config.hard_concurrency,
                "max_queued": self.config.max_queued,
                "weight": self.config.weight,
                "memory_limit_bytes": self.config.memory_limit_bytes,
                "memory_reserved_bytes": (
                    int(mem.reserved) if mem is not None else 0
                ),
                "total_admitted": self.total_admitted,
                "total_queued": self.total_queued,
            }


class GroupMemoryEscalation:
    """Per-group `on_exceeded` hook (installed on the group's sub-pool
    node): when a GROUP limit blocks a reservation, degrade strictly
    within the group — revoke the largest wave-capable operator whose
    memory lives under this group, then kill the group's own largest
    query — and NEVER touch a bystander group (the pool-root hook's
    cluster-wide largest-victim choice does not apply to group limits).
    Returning False propagates the exception to the requester, whose
    partition-wave fallback already plans against the group limit
    (spill.effective_budget walks the ancestor chain)."""

    def __init__(self, group_name: str):
        self.group_name = group_name
        #: (requesting group, victim query name) log — the chaos suite's
        #: zero-cross-group-kill witness
        self.kill_log: list = []

    @staticmethod
    def _under(ctx, group_node) -> bool:
        node = ctx
        while node is not None:
            if node is group_node:
                return True
            node = node.parent
        return False

    def __call__(self, group_node, requesting, delta: int) -> bool:
        from trino_tpu.runtime.spill import REVOCABLES

        # revoke tier, group-scoped: largest registered revocable whose
        # reservation lives under this group spills + releases
        for h in sorted(
            REVOCABLES.live(), key=lambda e: e.reserved_bytes(), reverse=True
        ):
            if h.ctx is None or not self._under(h.ctx, group_node):
                continue
            if h.revoke() > 0:
                from trino_tpu.telemetry.metrics import (
                    memory_revocations_counter,
                )

                memory_revocations_counter().inc()
                return True
        # kill tier, group-scoped: same largest-victim semantics as the
        # LowMemoryKiller, candidates restricted to THIS group's queries
        req_query = requesting.query_root()
        candidates = [
            q
            for q in getattr(group_node, "query_children", ())
            if q.reserved > 0
        ]
        if not candidates:
            return False
        victim = max(candidates, key=lambda q: q.reserved)
        if victim is req_query:
            # the requester holds the group's largest reservation: failing
            # its reservation IS the kill (degrades to waves, never shoots
            # a smaller in-group bystander, never ANY out-of-group query)
            return False
        from trino_tpu.telemetry.metrics import memory_kills_counter

        memory_kills_counter().inc()
        self.kill_log.append((self.group_name, victim.name))
        owner = getattr(victim, "owner", None)
        if owner is not None:
            owner.kill(
                "memory",
                detail=(
                    f"killed by resource group '{self.group_name}' memory "
                    f"limit: largest in-group reservation "
                    f"({victim.reserved} bytes) when {requesting.name} "
                    f"requested {delta} more"
                ),
            )
        victim.force_release()
        return True


#: resource-groups properties-file knob names -> ResourceGroupConfig field
_GROUP_KNOBS = {
    "max-concurrency": ("hard_concurrency", int),
    "hard-concurrency": ("hard_concurrency", int),
    "max-queued": ("max_queued", int),
    "weight": ("weight", int),
    "memory-limit-bytes": ("memory_limit_bytes", int),
}

_RG_PREFIX = "resource-groups."


class ResourceGroupManager:
    """Selector + group registry (InternalResourceGroupManager role).
    Selection: exact user match first, then the default group."""

    def __init__(self, default: Optional[ResourceGroupConfig] = None):
        self.groups: dict[str, ResourceGroup] = {}
        self.default = self.add(
            default or ResourceGroupConfig("global", hard_concurrency=1)
        )
        self._user_rules: dict[str, str] = {}

    @classmethod
    def from_properties(cls, props: Optional[dict] = None) -> "ResourceGroupManager":
        """Build a manager from ``resource-groups.*`` properties (the
        resource-group configuration manager's file format)::

            resource-groups.global.max-concurrency=4
            resource-groups.etl.weight=2
            resource-groups.etl.max-queued=16
            resource-groups.etl.memory-limit-bytes=268435456
            resource-groups.user.batch=etl

        Unknown knob names raise (a typo must not silently become an
        unlimited group); ``global`` stays the default selector target."""
        configs: dict[str, dict] = {}
        rules: dict[str, str] = {}
        for k, v in (props or {}).items():
            if not k.startswith(_RG_PREFIX):
                continue
            rest = k[len(_RG_PREFIX):]
            if rest.startswith("user."):
                rules[rest[len("user."):]] = str(v).strip()
                continue
            if "." not in rest:
                raise ValueError(f"malformed resource-group key: {k!r}")
            name, knob = rest.rsplit(".", 1)
            if knob not in _GROUP_KNOBS:
                raise ValueError(
                    f"unknown resource-group knob {knob!r} in {k!r} "
                    f"(supported: {sorted(_GROUP_KNOBS)})"
                )
            field_name, typ = _GROUP_KNOBS[knob]
            configs.setdefault(name, {})[field_name] = typ(v)
        mgr = cls(
            ResourceGroupConfig("global", **configs.pop("global", {}))
        )
        for name, kw in sorted(configs.items()):
            mgr.add(ResourceGroupConfig(name, **kw))
        for user, group in rules.items():
            if group not in mgr.groups:
                raise ValueError(
                    f"resource-groups.user.{user} names unknown group "
                    f"{group!r}"
                )
            mgr.add_user_rule(user, group)
        return mgr

    def add(self, config: ResourceGroupConfig) -> ResourceGroup:
        g = ResourceGroup(config)
        self.groups[config.name] = g
        return g

    def ensure(self, config: ResourceGroupConfig) -> ResourceGroup:
        """The group, creating it from `config` when absent (the
        dispatcher's system.prewarm bootstrap)."""
        g = self.groups.get(config.name)
        return g if g is not None else self.add(config)

    def add_user_rule(self, user: str, group_name: str) -> None:
        self._user_rules[user] = group_name

    def select(self, user: Optional[str] = None) -> ResourceGroup:
        if user is not None and user in self._user_rules:
            return self.groups[self._user_rules[user]]
        return self.default

    def stats(self) -> list:
        return [g.stats() for g in self.groups.values()]
