"""Resource groups: admission control and fair queuing.

Reference: execution/resourcegroups/InternalResourceGroup.java +
InternalResourceGroupManager.java — queries are admitted into a tree of
groups, each with hard concurrency and queue limits; queued queries start
as running ones finish.

Engine mapping: the scarce resource is the device, so `hard_concurrency`
bounds concurrent engine executions per group and `max_queued` bounds the
backlog.  A selector picks the group by user/source (the resource-group
manager plugin's role, reduced to prefix rules)."""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional


class QueryQueueFullError(RuntimeError):
    """Reference: QUERY_QUEUE_FULL error code."""


@dataclass
class ResourceGroupConfig:
    name: str
    hard_concurrency: int = 1
    max_queued: int = 100


class ResourceGroup:
    def __init__(self, config: ResourceGroupConfig):
        self.config = config
        self.running = 0
        self.queued: deque = deque()
        self.lock = threading.Lock()
        #: peak/telemetry counters (system.runtime-style observability)
        self.total_admitted = 0
        self.total_queued = 0

    def acquire(self, timeout: Optional[float] = None) -> None:
        """Block until admitted; raise QueryQueueFullError when the queue
        is at max_queued (reference: InternalResourceGroup.run).  A timeout
        ALWAYS raises TimeoutError: when the wait expires but release() has
        already signaled our gate (the timeout/grant race), the granted slot
        is handed to the next waiter under the lock instead of being
        swallowed by a caller that has given up — a leaked slot there
        permanently shrinks the group's effective concurrency."""
        gate = None
        with self.lock:
            if self.running < self.config.hard_concurrency:
                self.running += 1
                self.total_admitted += 1
                return
            if len(self.queued) >= self.config.max_queued:
                raise QueryQueueFullError(
                    f"resource group {self.config.name} queue is full "
                    f"({self.config.max_queued})"
                )
            gate = self._make_gate()
            self.queued.append(gate)
            self.total_queued += 1
        if not gate.wait(timeout=timeout):
            with self.lock:
                try:
                    self.queued.remove(gate)
                except ValueError:
                    # raced with release(): the slot was granted to us after
                    # we timed out — pass it on, we are no longer waiting
                    self.total_admitted -= 1  # the grant never ran
                    self._hand_off_locked()
            raise TimeoutError(
                f"queued in resource group {self.config.name} past timeout"
            )

    def _make_gate(self) -> threading.Event:
        """Seam for the timeout/grant race regression test (a gate whose
        wait() deterministically 'times out' after release() signals it)."""
        return threading.Event()

    def _hand_off_locked(self) -> None:  # lint: allow(unguarded-state)
        """Transfer one held slot onward (caller holds self.lock): wake the
        next waiter, or return the slot to the pool when nobody waits."""
        if self.queued:
            gate = self.queued.popleft()
            self.total_admitted += 1
            gate.set()
        else:
            self.running = max(0, self.running - 1)

    def release(self) -> None:
        with self.lock:
            self._hand_off_locked()

    def stats(self) -> dict:
        with self.lock:
            return {
                "name": self.config.name,
                "running": self.running,
                "queued": len(self.queued),
                "hard_concurrency": self.config.hard_concurrency,
                "total_admitted": self.total_admitted,
                "total_queued": self.total_queued,
            }


class ResourceGroupManager:
    """Selector + group registry (InternalResourceGroupManager role).
    Selection: exact user match first, then the default group."""

    def __init__(self, default: Optional[ResourceGroupConfig] = None):
        self.groups: dict[str, ResourceGroup] = {}
        self.default = self.add(
            default or ResourceGroupConfig("global", hard_concurrency=1)
        )
        self._user_rules: dict[str, str] = {}

    def add(self, config: ResourceGroupConfig) -> ResourceGroup:
        g = ResourceGroup(config)
        self.groups[config.name] = g
        return g

    def add_user_rule(self, user: str, group_name: str) -> None:
        self._user_rules[user] = group_name

    def select(self, user: Optional[str] = None) -> ResourceGroup:
        if user is not None and user in self._user_rules:
            return self.groups[self._user_rules[user]]
        return self.default

    def stats(self) -> list:
        return [g.stats() for g in self.groups.values()]
