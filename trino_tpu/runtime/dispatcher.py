"""Concurrent query serving: admission control + weighted-fair dispatch.

Reference roles: dispatcher/DispatchManager.java (the queued -> dispatched
query lifecycle, queue limits, shedding), execution/resourcegroups/
InternalResourceGroupManager (admission through weighted groups), and the
TaskExecutor time-slicing loop (SURVEY §5.7) — many queries share one
device by interleaving at fragment/batch boundaries, never by preemption.

Engine mapping.  The coordinator used to hold ONE global engine lock
around every statement (server/coordinator.py pre-PR-13): a cluster built
to serve millions of users executed exactly one statement at a time and
had no defined behavior under overload.  This module replaces the lock
with three coordinated tiers:

  * **Admission** — every statement enters a `ResourceGroup`'s FIFO queue
    (`enqueue`); a full queue SHEDS the statement (`QueryShedError`,
    surfaced as HTTP 429 + Retry-After before the request body is read);
    a statement queued past `query_max_queued_time` fails with
    EXCEEDED_QUEUED_TIME_LIMIT without ever occupying a lane; a DELETE on
    a queued query dequeues it without acquiring a slot.
  * **Weighted-fair scheduling** — the next statement comes from the
    eligible group (nonempty queue, below its `hard_concurrency`) with
    the smallest weighted virtual time, not from a global FIFO: saturated
    groups with weights w1:w2 converge to a w1:w2 admission ratio, and an
    idle group re-entering clamps to the global virtual clock so it gets
    its share immediately without starving everyone with banked credit.
  * **Engine lanes (time slicing)** — admitted statements run on `lanes`
    runner clones sharing the process trace cache, catalogs, tracker, and
    memory pool: host-side planning, analysis, and result serialization
    overlap across lanes, while actual device execution time-slices
    through the process-wide `device_slice()` gate at fragment/batch
    boundaries (feed/step/drain — SPMD launches stay serialized per
    device, no preemption).  Runners that cannot be cloned (multi-host)
    degrade to one lane: admission control and fairness still apply, and
    execution serializes exactly as before.

Memory: a group with `memory_limit_bytes` owns a sub-pool of the PR 12
shared MemoryContext tree; admitted queries reserve under it (the
contextvar `lifecycle.set_group_memory` routes `query_memory_context`),
so a group at its limit degrades through revoke -> wave -> kill WITHIN
the group (resource_groups.GroupMemoryEscalation) and can never kill a
bystander group's query.

Shutdown: `drain()` stops admission, fails every queued statement
classified (SERVER_SHUTTING_DOWN), waits `dispatcher.drain-wait` for
running ones, then force-kills stragglers through their lifecycle tokens
(the PR 8 bounded force-kill contract) and waits a short grace.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Optional

from trino_tpu.runtime.resource_groups import (
    SYSTEM_PREWARM_GROUP,
    ResourceGroup,
    ResourceGroupConfig,
    ResourceGroupManager,
)
from trino_tpu.telemetry.spans import now

#: process-wide device time-slice gate: one compiled program launches at a
#: time; host work (parse/plan/serialize) runs outside it.  An RLock so
#: nested statement execution (EXECUTE -> execute) re-enters freely.
_DEVICE_GATE = threading.RLock()

#: gate contention telemetry state.  _GATE_WAITERS is mutated only on the
#: CONTENDED acquire path (under _WAITERS_LOCK); _GATE_HOLDER/_GATE_DEPTH
#: are mutated only by the thread HOLDING the gate (the gate itself is
#: their lock).  Readers (the occupancy callback gauge, the release-path
#: `if _GATE_WAITERS` check) take snapshots of a single int — stale by at
#: most one step, never torn.
_WAITERS_LOCK = threading.Lock()
_GATE_WAITERS = 0
_GATE_HOLDER = -1
_GATE_DEPTH = 0


def gate_holder() -> int:
    """Engine lane currently holding the device gate (-1 = idle); feeds
    the trino_tpu_device_gate_occupied callback gauge."""
    return _GATE_HOLDER


def gate_waiters() -> int:
    """Lanes currently blocked in a contended device-gate acquire."""
    return _GATE_WAITERS


class _DeviceSlice:
    """One timed passage through the device gate (see device_slice()).

    Cost contract (the PR 12 zero-cost-when-idle bar, measured in
    tests/test_profile_store.py): the UNCONTENDED path is one non-blocking
    RLock acquire, ONE clock read, and two attribute writes per step — no
    histogram observe, no contextvar lookup beyond the holder label.  All
    wait accounting lives on the contended path, where the caller is about
    to block anyway; hold time is observed only when another lane waited
    during the hold (the contention-relevant holds)."""

    __slots__ = ("t_acq",)

    def __enter__(self):
        global _GATE_WAITERS, _GATE_HOLDER, _GATE_DEPTH
        if _DEVICE_GATE.acquire(blocking=False):
            self.t_acq = now()  # the one uncontended clock read
        else:
            from trino_tpu.runtime import lifecycle
            from trino_tpu.telemetry.metrics import gate_wait_histogram

            t0 = now()
            with _WAITERS_LOCK:
                _GATE_WAITERS += 1
            try:
                _DEVICE_GATE.acquire()
            finally:
                with _WAITERS_LOCK:
                    _GATE_WAITERS -= 1
            self.t_acq = now()
            wait = self.t_acq - t0
            gate_wait_histogram().observe(wait)
            lifecycle.note_gate_wait(wait)
        # depth/holder are guarded by the gate itself (holder-only writes)
        _GATE_DEPTH += 1
        if _GATE_DEPTH == 1:
            from trino_tpu.runtime.lifecycle import current_lane

            _GATE_HOLDER = current_lane()
        return self

    def __exit__(self, et, ev, tb):
        global _GATE_HOLDER, _GATE_DEPTH
        _GATE_DEPTH -= 1
        if _GATE_DEPTH == 0:
            _GATE_HOLDER = -1
            if _GATE_WAITERS:
                from trino_tpu.telemetry.metrics import gate_hold_histogram

                gate_hold_histogram().observe(now() - self.t_acq)
        _DEVICE_GATE.release()
        return False


def device_slice():
    """The device time-slice gate (a reentrant, TIMED context manager):
    lanes acquire it around each execution step — pipeline construction
    and per-batch pulls — so concurrent queries interleave device work at
    fragment/batch boundaries instead of contending mid-kernel.

    Telemetry: contended acquires observe
    `trino_tpu_device_gate_wait_seconds` and fold into the executing
    query's `gate_wait_s` (QueryStatistics, the query trace, and the
    archived profile); holds during which another lane waited observe
    `trino_tpu_device_gate_hold_seconds`; the holding lane is readable as
    the `trino_tpu_device_gate_occupied{lane}` pull gauge.  Uncontended
    (single lane / no dispatcher) a step costs one non-blocking RLock
    acquire + one clock read: noise."""
    return _DeviceSlice()


class QueryShedError(RuntimeError):
    """Resource-group queue full: the statement is shed (HTTP 429 with
    Retry-After) instead of queued — a RETRYABLE client error, the
    defined overload behavior."""

    error_code = "QUERY_QUEUE_FULL"
    retryable = True

    def __init__(self, group: str, retry_after_s: float):
        super().__init__(
            f"resource group {group} queue is full; retry after "
            f"{retry_after_s:.1f}s"
        )
        self.group = group
        self.retry_after_s = retry_after_s


class DispatcherStoppedError(RuntimeError):
    """The dispatcher is draining/stopped: queued statements fail
    classified instead of hanging."""

    error_code = "SERVER_SHUTTING_DOWN"

    def __init__(self, detail: str = "coordinator is shutting down"):
        super().__init__(detail)


# -- tickets -------------------------------------------------------------------

QUEUED = "QUEUED"
ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
DONE = "DONE"
CANCELED = "CANCELED"
EXPIRED = "EXPIRED"
STOPPED = "STOPPED"


class AdmissionTicket:
    """One statement's place in the admission queue.  All state
    transitions happen under the DISPATCHER's lock (the ticket itself has
    none); `wait()` blocks the statement thread until an engine lane is
    granted or the ticket resolves canceled/expired/stopped."""

    __slots__ = (
        "dispatcher", "group_name", "state", "event", "lane",
        "enqueued_at", "admitted_at", "deadline", "lane0_required",
        "on_force_kill", "queued_s", "_observed",
    )

    def __init__(self, dispatcher: "QueryDispatcher", group_name: str,
                 deadline: Optional[float], lane0_required: bool = False):
        self.dispatcher = dispatcher
        self.group_name = group_name
        self.state = QUEUED
        self.event = threading.Event()
        self.lane = None
        self.enqueued_at = dispatcher._clock()
        self.admitted_at: Optional[float] = None
        self.deadline = deadline
        self.lane0_required = lane0_required
        #: called by drain() on a still-running statement past the drain
        #: deadline (the coordinator wires the query's cancel here)
        self.on_force_kill: Optional[Callable[[], None]] = None
        self.queued_s = 0.0
        self._observed = False

    def wait(self):
        """Block until admitted; returns the granted engine lane.  Raises
        the classified outcome otherwise: QueryCanceledException
        (cancel-while-queued), QueryQueuedTimeExceeded
        (query_max_queued_time), DispatcherStoppedError (drain)."""
        from trino_tpu.runtime.lifecycle import (
            QueryCanceledException,
            QueryQueuedTimeExceeded,
        )

        d = self.dispatcher
        while True:
            with d._lock:
                st = self.state
            if st in (ADMITTED, RUNNING):
                return self.lane
            if st == CANCELED:
                raise QueryCanceledException(
                    f"query canceled while queued in resource group "
                    f"{self.group_name}"
                )
            if st == EXPIRED:
                raise QueryQueuedTimeExceeded(
                    f"query exceeded query_max_queued_time in resource "
                    f"group {self.group_name} "
                    f"({(self.deadline or 0) - self.enqueued_at:.3f}s)"
                )
            if st == STOPPED:
                raise DispatcherStoppedError(
                    "query failed while queued: coordinator is shutting "
                    "down"
                )
            remaining = None
            if self.deadline is not None:
                remaining = self.deadline - d._clock()
                if remaining <= 0:
                    with d._lock:
                        if self.state == QUEUED:
                            self.state = EXPIRED
                            d._dequeue_locked(self)
                    continue
            self.event.wait(remaining)

    def cancel(self) -> None:
        """Queued-query cancel (DELETE /v1/query/{id} racing admission):
        a QUEUED ticket dequeues without ever acquiring a slot; a ticket
        that WON the admission race but has not started running hands its
        lane and group slot straight back — either way the statement
        never consumes engine time."""
        self.dispatcher._cancel_ticket(self)


class _Lane:
    """One engine lane: a runner the dispatcher grants to admitted
    statements, one at a time.  Lane 0 is the primary runner (the one
    system tables, prewarm, and membership live on); higher lanes are
    `clone_for_dispatch` clones sharing its catalogs/tracker/caches."""

    __slots__ = ("index", "runner", "busy")

    def __init__(self, index: int, runner):
        self.index = index
        self.runner = runner
        self.busy = False


class _GroupSched:
    """Dispatcher-side scheduling state for one resource group.  Mutated
    ONLY under the dispatcher lock; the group's `running` admission
    counter stays on the ResourceGroup (shared with the legacy blocking
    acquire() path, so both admission surfaces see one limit)."""

    __slots__ = ("group", "queue", "vtime", "shed_total", "queued_total")

    def __init__(self, group: ResourceGroup):
        self.group = group
        self.queue: deque = deque()
        self.vtime = 0.0
        self.shed_total = 0
        self.queued_total = 0


class QueryDispatcher:
    """See module docstring.  One per coordinator; the runner exposes it
    as `runner.dispatcher` so `system.runtime.resource_groups` can read
    live admission state over SQL."""

    def __init__(self, runner, groups: Optional[ResourceGroupManager] = None,
                 lanes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from trino_tpu.config import get_config
        from trino_tpu.telemetry.metrics import (
            queries_queued_gauge,
            queries_running_gauge,
            queries_shed_counter,
        )

        self.groups = groups or ResourceGroupManager()
        # prewarm replays admit through a dedicated weight-capped group
        # instead of holding an engine lock (PR 8 gap): a post-grow replay
        # waits its fair turn and cannot starve live user queries
        self.groups.ensure(
            ResourceGroupConfig(
                SYSTEM_PREWARM_GROUP, hard_concurrency=1, max_queued=8,
                weight=1,
            )
        )
        self._clock = clock
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        cfg = get_config().dispatcher
        n = int(lanes) if lanes is not None else max(1, int(cfg.lanes))
        self._lanes = [_Lane(0, runner)]
        for i in range(1, n):
            clone = None
            maker = getattr(runner, "clone_for_dispatch", None)
            if maker is not None:
                clone = maker()
            if clone is None:
                break  # not cloneable (multi-host): single lane
            self._lanes.append(_Lane(i, clone))
        self._sched: dict[str, _GroupSched] = {}
        for name, g in self.groups.groups.items():
            self._sched[name] = _GroupSched(g)
            # a LEGACY ResourceGroup.release() (dbapi holders) must also
            # wake tickets queued in the dispatcher — both admission
            # surfaces share the slot counter, so both must schedule
            g.on_slot_freed = self._kick
            queries_queued_gauge().labels(name).set(0)
            queries_running_gauge().labels(name).set(0)
            queries_shed_counter().labels(name).inc(0)
        self._vtime = 0.0
        self._running: set = set()
        self._stopped = False
        #: immutable post-construction aliases for lock-free reads (the
        #: _lanes LIST itself is only walked under the dispatcher lock)
        self._primary = runner
        self._n_lanes = len(self._lanes)
        # group memory sub-pools attach to the shared pool root eagerly so
        # limits bind from the first admitted statement
        from trino_tpu.runtime.lifecycle import memory_pool

        root = memory_pool().root
        for g in self.groups.groups.values():
            g.memory_context(root)

    @property
    def lanes(self) -> int:
        return self._n_lanes

    @property
    def runner(self):
        return self._primary

    # -- admission -------------------------------------------------------------

    def _group_for(self, user: Optional[str],
                   group_name: Optional[str]) -> _GroupSched:
        if group_name is not None:
            group = self.groups.groups[group_name]
        else:
            group = self.groups.select(user)
        with self._lock:
            gs = self._sched.get(group.config.name)
            if gs is None:  # a group added after construction (tests)
                gs = self._sched.setdefault(
                    group.config.name, _GroupSched(group)
                )
                group.on_slot_freed = self._kick
        return gs

    def _retry_after(self) -> float:
        from trino_tpu.config import get_config

        return float(get_config().dispatcher.retry_after_s)

    def _kick(self) -> None:
        """Scheduling pass triggered from outside the dispatcher (a legacy
        ResourceGroup.release freeing a shared slot).  Reentrant-safe: the
        dispatcher's own release path may reach here while already holding
        the (R)lock."""
        with self._lock:
            self._schedule_locked()
            self._cv.notify_all()

    def _can_start_now_locked(  # lint: allow(unguarded-state)
            self, gs: _GroupSched, lane0_required: bool = False) -> bool:
        """Caller holds self._lock."""
        if self._stopped or gs.queue:
            return False
        if lane0_required:
            if self._lanes[0].busy:
                return False
        elif not any(not l.busy for l in self._lanes):
            return False
        return gs.group.has_slot()

    def shed_probe(self, user: Optional[str] = None) -> Optional[float]:
        """The PRE-BODY overload check (HTTP 429 path): None = admit or
        queue normally; a float = shed, answer 429 with this Retry-After.
        Bumps the group's shed counter — a probe that sheds IS the shed
        event (the request body is never read, no ticket exists)."""
        gs = self._group_for(user, None)
        with self._lock:
            if self._stopped:
                return None  # submit path answers SERVER_SHUTTING_DOWN
            if len(gs.queue) < gs.group.config.max_queued:
                return None
            if self._can_start_now_locked(gs):
                return None
            return self._shed_locked(gs)

    def _shed_locked(self, gs: _GroupSched) -> float:
        from trino_tpu.telemetry.metrics import queries_shed_counter

        gs.shed_total += 1
        queries_shed_counter().labels(gs.group.config.name).inc()
        return self._retry_after()

    def enqueue(self, user: Optional[str] = None,
                group_name: Optional[str] = None,
                queue_deadline_s: Optional[float] = None,
                lane0_required: bool = False) -> AdmissionTicket:
        """Admit-or-queue one statement; returns its ticket (wait() blocks
        for the lane).  Raises QueryShedError when the group's queue is
        full and no slot is immediately free; DispatcherStoppedError when
        draining.  `queue_deadline_s` defaults to the primary runner's
        query_max_queued_time session property."""
        gs = self._group_for(user, group_name)
        group = gs.group
        if queue_deadline_s is None:
            try:
                queue_deadline_s = float(
                    self.runner.properties.get("query_max_queued_time")
                )
            except (AttributeError, KeyError):
                queue_deadline_s = 0.0
        deadline = (
            self._clock() + queue_deadline_s if queue_deadline_s > 0 else None
        )
        from trino_tpu.telemetry.metrics import queries_queued_gauge

        with self._lock:
            if self._stopped:
                raise DispatcherStoppedError()
            if (
                len(gs.queue) >= group.config.max_queued
                and not self._can_start_now_locked(gs, lane0_required)
            ):
                raise QueryShedError(
                    group.config.name, self._shed_locked(gs)
                )
            t = AdmissionTicket(
                self, group.config.name, deadline, lane0_required
            )
            gs.queue.append(t)
            gs.queued_total += 1
            with group.lock:
                group.total_queued += 1
            queries_queued_gauge().labels(group.config.name).set(
                len(gs.queue)
            )
            self._schedule_locked()
        return t

    def _dequeue_locked(self, t: AdmissionTicket) -> None:  # lint: allow(unguarded-state)
        """Caller holds self._lock.  Remove a no-longer-QUEUED ticket from its group queue and
        publish its queue-wait (caller already moved t.state)."""
        from trino_tpu.telemetry.metrics import queries_queued_gauge

        gs = self._sched[t.group_name]
        try:
            gs.queue.remove(t)
        except ValueError:
            pass
        queries_queued_gauge().labels(t.group_name).set(len(gs.queue))
        self._observe_queued_locked(t)
        t.event.set()
        self._schedule_locked()

    def _observe_queued_locked(self, t: AdmissionTicket) -> None:  # lint: allow(unguarded-state)
        """Caller holds self._lock."""
        from trino_tpu.telemetry.metrics import query_queued_histogram

        if not t._observed:
            t._observed = True
            t.queued_s = max(0.0, self._clock() - t.enqueued_at)
            query_queued_histogram().observe(t.queued_s)

    def _cancel_ticket(self, t: AdmissionTicket) -> None:
        from trino_tpu.telemetry.metrics import queries_running_gauge

        with self._lock:
            if t.state == QUEUED:
                t.state = CANCELED
                self._dequeue_locked(t)
                return
            if t.state == ADMITTED:
                # cancel WON the race against a concurrent grant: hand the
                # lane and group slot straight back — the statement never
                # ran, the slot wakes the next queued ticket
                t.state = CANCELED
                lane = t.lane
                if lane is not None:
                    lane.busy = False
                    t.lane = None
                self._running.discard(t)
                gs = self._sched[t.group_name]
                gs.group.release()
                queries_running_gauge().labels(t.group_name).set(
                    self._running_in_group(t.group_name)
                )
                t.event.set()
                self._schedule_locked()
                self._cv.notify_all()
            # RUNNING/terminal: the lifecycle token owns cancellation

    def _running_in_group(self, name: str) -> int:  # lint: allow(unguarded-state)
        """Caller holds self._lock."""
        return sum(1 for r in self._running if r.group_name == name)

    # -- weighted-fair scheduling ----------------------------------------------

    def _schedule_locked(self) -> None:  # lint: allow(unguarded-state)
        """Caller holds self._lock.  Grant free lanes to queued tickets, next eligible group by
        smallest weighted virtual time (WFQ): an admission charges the
        group 1/weight of virtual service, and a group going backlogged
        clamps to the global virtual clock so banked idle credit cannot
        starve the others."""
        from trino_tpu.telemetry.metrics import (
            queries_queued_gauge,
            queries_running_gauge,
        )

        while not self._stopped:
            free = [l for l in self._lanes if not l.busy]
            if not free:
                return
            best = None
            for name, gs in sorted(self._sched.items()):
                if not gs.queue:
                    continue
                head = gs.queue[0]
                if head.lane0_required and self._lanes[0].busy:
                    continue
                if not gs.group.has_slot():
                    continue
                if best is None or gs.vtime < best[0]:
                    best = (gs.vtime, name, gs, head)
            if best is None:
                return
            _, name, gs, t = best
            if not gs.group.try_acquire_now():
                continue  # raced a legacy acquire(); re-evaluate
            lane = self._lanes[0] if t.lane0_required else free[-1]
            lane.busy = True
            t.lane = lane
            t.state = ADMITTED
            t.admitted_at = self._clock()
            gs.queue.popleft()
            self._running.add(t)
            # virtual-time bookkeeping: service starts at the later of the
            # group's own clock and the global clock (idle catch-up), and
            # costs 1/weight
            start = max(gs.vtime, self._vtime)
            gs.vtime = start + 1.0 / max(1, gs.group.config.weight)
            self._vtime = start
            queries_queued_gauge().labels(name).set(len(gs.queue))
            queries_running_gauge().labels(name).set(
                self._running_in_group(name)
            )
            self._observe_queued_locked(t)
            t.event.set()

    # -- execution -------------------------------------------------------------

    def run_admitted(self, ticket: AdmissionTicket, fn):
        """Run `fn(lane_runner)` on the ticket's granted lane, under the
        group's memory sub-pool and admission contextvars; releases the
        lane + slot and schedules the next ticket when done."""
        from trino_tpu.runtime import lifecycle

        with self._lock:
            if ticket.state == CANCELED:
                # DELETE slipped between wait() returning and execution
                # starting: the cancel path already handed the slot back
                raise lifecycle.QueryCanceledException(
                    "query canceled before execution started"
                )
            if ticket.state != ADMITTED:
                raise RuntimeError(
                    f"ticket is {ticket.state}, not ADMITTED"
                )
            ticket.state = RUNNING
            lane = ticket.lane
            gs = self._sched[ticket.group_name]
        primary = self._primary
        group_mem = gs.group.memory_context(
            lifecycle.memory_pool().root
        )
        tok_mem = lifecycle.set_group_memory(group_mem)
        tok_adm = lifecycle.set_admission_info(
            (ticket.group_name, ticket.queued_s)
        )
        # lane identity for the device-gate occupancy gauge: the statement
        # thread's device_slice() passages report this lane as the holder
        tok_lane = lifecycle.set_lane(lane.index)
        session_before = getattr(primary, "session", None)
        if lane.runner is not primary and session_before is not None:
            # lanes inherit the primary's catalog/schema; a USE executed on
            # a lane publishes back (last writer wins, like the shared
            # pre-dispatcher runner)
            lane.runner.session = session_before
        try:
            return fn(lane.runner)
        finally:
            if (
                lane.runner is not primary
                and getattr(lane.runner, "session", None) is not session_before
            ):
                primary.session = lane.runner.session
            lifecycle.reset_lane(tok_lane)
            lifecycle.reset_admission_info(tok_adm)
            lifecycle.reset_group_memory(tok_mem)
            self.release(ticket)

    def release(self, ticket: AdmissionTicket) -> None:
        from trino_tpu.telemetry.metrics import queries_running_gauge

        with self._lock:
            if ticket.state in (DONE, CANCELED):
                return  # already released (idempotent; cancel handed back)
            ticket.state = DONE
            lane = ticket.lane
            if lane is not None:
                lane.busy = False
                ticket.lane = None
            self._running.discard(ticket)
            gs = self._sched[ticket.group_name]
            gs.group.release()
            queries_running_gauge().labels(ticket.group_name).set(
                self._running_in_group(ticket.group_name)
            )
            self._schedule_locked()
            self._cv.notify_all()

    def system_admission(self):
        """Context manager for engine-internal work (prewarm replays):
        admits through the weight-capped `system.prewarm` group onto the
        PRIMARY lane — a fair queue participant, never a lock that jumps
        ahead of live user statements.  While the replay holds lane 0,
        other lanes keep serving users."""
        return _SystemAdmission(self)

    # -- shutdown --------------------------------------------------------------

    def drain(self, wait_s: Optional[float] = None,
              grace_s: Optional[float] = None) -> bool:
        """Stop admission, fail queued statements classified, wait
        `wait_s` for running ones, force-kill stragglers through their
        lifecycle tokens, wait `grace_s` more.  Returns True when every
        lane is idle at exit (a clean drain)."""
        from trino_tpu.config import get_config
        from trino_tpu.telemetry.metrics import queries_queued_gauge

        cfg = get_config().dispatcher
        wait_s = cfg.drain_wait_s if wait_s is None else wait_s
        grace_s = cfg.drain_grace_s if grace_s is None else grace_s
        with self._lock:
            self._stopped = True
            for name, gs in self._sched.items():
                while gs.queue:
                    t = gs.queue.popleft()
                    t.state = STOPPED
                    self._observe_queued_locked(t)
                    t.event.set()
                queries_queued_gauge().labels(name).set(0)
        self._wait_idle(self._clock() + wait_s)
        with self._lock:
            leftovers = list(self._running)
        if leftovers:
            from trino_tpu.telemetry.metrics import (
                drain_force_kills_counter,
            )

            for t in leftovers:
                cb = t.on_force_kill
                if cb is not None:
                    drain_force_kills_counter().inc()
                    try:
                        cb()
                    except Exception:
                        pass
            self._wait_idle(self._clock() + grace_s)
        with self._lock:
            return not self._running

    def _wait_idle(self, deadline: float) -> None:
        with self._lock:
            while self._running:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return
                # the condition shares self._lock, so wait() releases it
                self._cv.wait(timeout=min(remaining, 0.25))

    # -- observability ---------------------------------------------------------

    def stats(self) -> list:
        """Per-group admission state (system.runtime.resource_groups)."""
        with self._lock:
            out = []
            for name, gs in sorted(self._sched.items()):
                s = gs.group.stats()
                s["queued"] = len(gs.queue)
                s["running"] = self._running_in_group(name)
                s["shed_total"] = gs.shed_total
                s["dispatcher_queued_total"] = gs.queued_total
                out.append(s)
            return out

    def retry_after_hint(self) -> int:
        return max(1, int(math.ceil(self._retry_after())))


class _SystemAdmission:
    """The prewarm-replay admission gate (QueryDispatcher.system_admission):
    enqueue into system.prewarm, wait for the primary lane, hold it for
    the with-block, release on exit."""

    def __init__(self, dispatcher: QueryDispatcher):
        self.dispatcher = dispatcher
        self.ticket: Optional[AdmissionTicket] = None

    def __enter__(self):
        d = self.dispatcher
        self.ticket = d.enqueue(
            group_name=SYSTEM_PREWARM_GROUP, queue_deadline_s=0.0,
            lane0_required=True,
        )
        self.ticket.wait()
        with d._lock:
            self.ticket.state = RUNNING
        return d.runner

    def __exit__(self, et, ev, tb):
        self.dispatcher.release(self.ticket)
        return False
