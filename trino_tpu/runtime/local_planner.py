"""Local execution planner: logical PlanNode tree -> operator pipelines.

Reference role: sql/planner/LocalExecutionPlanner.java:516,600 (the seam where
plan nodes become OperatorFactory chains and symbols are laid out as channels).
Here each plan node becomes a (batch-stream, symbol-layout) pair; symbol
references inside expressions are rewritten to positional InputRef channels
exactly like the reference's layout mapping, and join build sides are
materialized by draining their subplan (HashBuilderOperator's role).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column
from trino_tpu.columnar.batch import device_get_async
from trino_tpu.connectors.api import CatalogManager
from trino_tpu.expr.ir import (
    Call,
    Expr,
    Form,
    InputRef,
    Literal,
    SpecialForm,
    SymbolRef,
    visit,
)
from trino_tpu.expr import ExprCompiler
from trino_tpu.ops.aggregation import AggregationOperator, AggSpec
from trino_tpu.ops.common import SortKey
from trino_tpu.ops.filter_project import FilterProjectOperator
from trino_tpu.ops.join import HashJoinOperator, NestedLoopJoinOperator, SemiJoinOperator
from trino_tpu.ops.scan import ScanOperator
from trino_tpu.ops.sort import LimitOperator, OrderByOperator, TopNOperator
from trino_tpu.ops.values import ValuesOperator
from trino_tpu.planner import plan as P
from trino_tpu.planner.functions import HOLISTIC_AGGS


class PhysicalPlan:
    """A batch stream plus the symbol layout of its channels."""

    def __init__(self, stream: Iterable[Batch], symbols: list):
        self.stream = stream
        self.symbols = list(symbols)

    def channel(self, name: str) -> int:
        for i, s in enumerate(self.symbols):
            if s.name == name:
                return i
        raise KeyError(f"symbol {name} not in layout {[s.name for s in self.symbols]}")

    def rewrite(self, expr: Expr) -> Expr:
        """SymbolRef -> InputRef against this layout."""

        def fn(e: Expr) -> Expr:
            if isinstance(e, SymbolRef):
                return InputRef(self.channel(e.name), e.type)
            return e

        return visit(expr, fn)

    def identity_projections(self) -> list:
        return [InputRef(i, s.type) for i, s in enumerate(self.symbols)]

    def types(self) -> list:
        return [s.type for s in self.symbols]


class LocalExecutionPlanner:
    def __init__(
        self,
        catalogs: CatalogManager,
        target_splits: int = 4,
        stats=None,
        properties=None,
    ):
        from trino_tpu.runtime.lifecycle import query_memory_context
        from trino_tpu.runtime.session import SessionProperties

        self.catalogs = catalogs
        self.target_splits = target_splits
        self.stats = stats  # Optional[StatsCollector] for EXPLAIN ANALYZE
        self.properties = properties or SessionProperties()
        #: per-query device-memory budget tree (reference:
        #: lib/trino-memory-context AggregatedMemoryContext + MemoryPool);
        #: blocking operators reserve through children of this context.
        #: When a query is executing this lives on the SHARED process pool,
        #: where the revoke tier and the LowMemoryKiller can see it.
        self.memory = query_memory_context(self._session_budget())
        if stats is not None:
            stats.memory = self.memory
        self._depth = 0
        #: symbol name -> (lo, hi) host values collected from materialized
        #: join build sides (reference: server/DynamicFilterService.java:107 +
        #: DynamicFilterSourceOperator — build-side ranges prune probe scans)
        self.dynamic_filters: dict = {}

    def _session_budget(self) -> int:
        """Per-query session budget in bytes (query_max_memory / legacy
        query_max_memory_bytes, whichever is tighter)."""
        from trino_tpu.runtime.spill import session_budget

        return session_budget(self.properties)

    def _budget(self) -> int:
        """The effective device budget blocking operators plan against:
        session budget AND any shared pool limit (memory.pool-limit-bytes),
        whichever is tighter.  0 = unconstrained — no wave machinery runs."""
        from trino_tpu.runtime.spill import effective_budget

        return effective_budget(self.properties, self.memory)

    def _observer(self):
        """Wave/spill event sink: the metrics registry plus EXPLAIN
        ANALYZE's StatsCollector counters when one is attached."""
        from trino_tpu.runtime.spill import PressureObserver

        return PressureObserver(sink=self.stats)

    def _make_spiller(self):
        """A filesystem-SPI spill store for one wave operation, or None
        when the `spill_enabled` session knob stages partitions in host
        RAM instead.  Callers invoke this LAZILY (first spill), so an
        unconstrained query never touches the filesystem."""
        from trino_tpu.runtime.spill import SpillManager, spill_to_disk

        if not spill_to_disk(self.properties):
            return None
        return SpillManager(observer=self._observer())

    def plan(self, node: P.PlanNode) -> PhysicalPlan:
        method = getattr(self, "_visit_" + type(node).__name__, None)
        if method is None:
            raise NotImplementedError(f"no local plan for {type(node).__name__}")
        self._depth += 1
        try:
            out = method(node)
        finally:
            self._depth -= 1
        if self.stats is not None:
            st = self.stats.register(
                type(node).__name__.replace("Node", ""), depth=self._depth
            )
            out = PhysicalPlan(self.stats.instrument(st, out.stream), out.symbols)
        return out

    # -- leaves ---------------------------------------------------------------

    def _visit_TableScanNode(self, node: P.TableScanNode) -> PhysicalPlan:
        connector = self.catalogs.get(node.handle.catalog)
        names = [c for _, c in node.assignments]
        types = [s.type for s, _ in node.assignments]
        from trino_tpu.connectors.api import scan_predicate_triples

        splits = list(
            connector.splits(
                node.handle,
                target_splits=self.target_splits,
                predicate=scan_predicate_triples(node),
            )
        )
        page_rows = self.properties.get("page_rows")
        use_cache = self.properties.get("scan_cache")
        prefetch_depth = self.properties.get("scan_prefetch_depth")
        concurrency = self.properties.get("task_concurrency")

        def split_feed(split):
            def make():
                from trino_tpu.runtime.retry import FAILURE_INJECTOR

                FAILURE_INJECTOR.maybe_fail(
                    f"scan:{node.handle.schema}.{node.handle.table}:{split.seq}"
                )
                op = ScanOperator(
                    connector, split, names, types,
                    page_rows=page_rows, use_cache=use_cache,
                )
                return op.batches()

            return make

        if concurrency > 1 and len(splits) > 1:
            # intra-task parallelism: split readers drain through a local
            # exchange (host-side decode+feed is the parallelizable part;
            # the device stream stays single — XLA owns that).  The exchange
            # is already background-fed + buffered, so no prefetch wrap.
            from trino_tpu.runtime.local_exchange import parallel_feed

            feed = parallel_feed(
                [split_feed(s) for s in splits], workers=concurrency
            )
        else:

            def stream():
                for split in splits:
                    yield from split_feed(split)()

            feed = stream()
            if prefetch_depth > 0:
                from trino_tpu.runtime.prefetch import prefetch_iter

                feed = prefetch_iter(feed, depth=prefetch_depth)
        plan = PhysicalPlan(feed, [s for s, _ in node.assignments])
        pred_expr = node.pushed_predicate
        # dynamic filters registered by upstream join builds (ranges over this
        # scan's output symbols) fuse into the scan's first device step
        dyn = []
        for s, _ in node.assignments:
            rng = self.dynamic_filters.get(s.name)
            if rng is not None:
                dyn.append(_range_expr(s, *rng))
        if dyn:
            from trino_tpu.expr.ir import and_

            pred_expr = and_(*(([pred_expr] if pred_expr is not None else []) + dyn))
        if pred_expr is not None:
            pred = plan.rewrite(pred_expr)
            fp = FilterProjectOperator(pred, plan.identity_projections())
            plan = PhysicalPlan(fp.process(plan.stream), plan.symbols)
        if dyn:
            # dynamic filters are usually very selective; compact so the
            # smaller live set shrinks every downstream static shape
            plan = PhysicalPlan(_compact_stream(plan.stream), plan.symbols)
        return plan

    def _visit_ValuesNode(self, node: P.ValuesNode) -> PhysicalPlan:
        op = ValuesOperator([s.type for s in node.symbols], node.rows)
        return PhysicalPlan(op.batches(), node.symbols)

    # -- row transforms -------------------------------------------------------

    def _visit_FilterNode(self, node: P.FilterNode) -> PhysicalPlan:
        src = self.plan(node.source)
        op = FilterProjectOperator(src.rewrite(node.predicate), src.identity_projections())
        return PhysicalPlan(op.process(src.stream), src.symbols)

    def _visit_ProjectNode(self, node: P.ProjectNode) -> PhysicalPlan:
        src = self.plan(node.source)
        if node.is_identity():
            return PhysicalPlan(src.stream, [s for s, _ in node.assignments])
        exprs = [src.rewrite(e) for _, e in node.assignments]
        op = FilterProjectOperator(None, exprs)
        return PhysicalPlan(op.process(src.stream), [s for s, _ in node.assignments])

    def _visit_UnnestNode(self, node: P.UnnestNode) -> PhysicalPlan:
        from trino_tpu.ops.unnest import UnnestOperator

        src = self.plan(node.source)
        exprs = [src.rewrite(e) for _, e in node.unnest]
        op = UnnestOperator(exprs, with_ordinality=node.ordinality is not None)
        return PhysicalPlan(op.process(src.stream), node.outputs)

    def _visit_SampleNode(self, node: "P.SampleNode") -> PhysicalPlan:
        from trino_tpu.ops.sample import SampleOperator

        src = self.plan(node.source)
        # deterministic per plan position: re-planning the same query (or a
        # retried fragment) samples the same rows
        self._sample_seq = getattr(self, "_sample_seq", 0) + 1
        op = SampleOperator(node.ratio, seed=self._sample_seq)
        return PhysicalPlan(op.process(src.stream), src.symbols)

    def _visit_PatternRecognitionNode(
        self, node: P.PatternRecognitionNode
    ) -> PhysicalPlan:
        from trino_tpu.ops.pattern import PatternRecognitionOperator

        src = self.plan(node.source)
        # defines rewritten to channel space over the SOURCE layout
        rewritten = P.PatternRecognitionNode(
            node.source,
            node.partition_by,
            node.order_by,
            [(v, src.rewrite(e)) for v, e in node.defines],
            node.pattern,
            node.measures,
            node.rows_per_match,
            node.after_match,
        )
        op = PatternRecognitionOperator(rewritten, src.symbols)
        return PhysicalPlan(op.process(src.stream), node.outputs)

    # -- aggregation ----------------------------------------------------------

    def _collapse_agg_source(self, node: P.AggregationNode):
        """Fold a Project*/Filter? chain under an aggregation into the
        aggregation's own input projection (classic projection merging), so
        the whole filter+compute+partial-reduce pipeline compiles as ONE
        XLA program — no intermediate column materialization.  Returns
        (source PhysicalPlan proxy, predicate Expr or None), or None when
        the shape doesn't match."""
        from trino_tpu.expr.ir import substitute_symbols

        maps = []
        inner = node.source
        while isinstance(inner, P.ProjectNode):
            maps.append({s.name: e for s, e in inner.assignments})
            inner = inner.source
        pred = None
        if isinstance(inner, P.FilterNode):
            pred = inner.predicate
            inner = inner.source
        if not maps and pred is None:
            return None
        if not isinstance(inner, P.TableScanNode):
            # conservative: only collapse over scans (other sources may have
            # their own operators with observable behavior)
            return None
        base = self.plan(inner)

        class _Sub:
            stream = base.stream
            symbols = base.symbols

            @staticmethod
            def rewrite(e):
                for m in maps:
                    e = substitute_symbols(e, m)
                return base.rewrite(e)

            @staticmethod
            def channel(name):
                return base.channel(name)

        pred_ir = base.rewrite(pred) if pred is not None else None
        return _Sub, pred_ir

    def _visit_AggregationNode(self, node: P.AggregationNode) -> PhysicalPlan:
        distinct = any(agg.distinct for _, agg in node.aggregations)
        collapsed = None if distinct else self._collapse_agg_source(node)
        if collapsed is not None:
            src, fused_pred = collapsed
        else:
            src = self.plan(node.source)
            fused_pred = None
        if distinct:
            src = self._distinct_preagg(node, src)
        ngroups = len(node.group_symbols)
        proj, specs, input_types = build_agg_inputs(node, src)
        pre = FilterProjectOperator(fused_pred, proj)
        # holistic aggregates need every group row at once: no streaming
        # partials (reference: ArrayAggregationFunction group state)
        streaming = not any(
            s.name in HOLISTIC_AGGS for s in specs
        )

        budget = self._budget()
        # Fuse the agg-input projection INTO the jitted partial-reduce
        # program when possible: projection outputs (decimal products etc.)
        # then never materialize between operators — the whole-fragment
        # fusion XLA is built for.  Group keys must be identity InputRefs so
        # host-side direct-path eligibility can read the RAW batch.
        from trino_tpu.expr.ir import InputRef

        pre_raw = pre_key = group_src = None
        if streaming and not (budget and ngroups):
            if all(isinstance(proj[i], InputRef) for i in range(ngroups)):
                pre_raw, pre_key = pre.fusable_step()
                if pre_raw is not None:
                    group_src = [proj[i].channel for i in range(ngroups)]

        def make_op():
            op = AggregationOperator(
                list(range(ngroups)),
                specs,
                input_types,
                mode=node.step,
                streaming=streaming,
                fold_every=self.properties.get("agg_fold_batches"),
                memory_ctx=self.memory.child("aggregation"),
                use_pallas=self.properties.get("pallas_agg"),
                pre_step=pre_raw,
                pre_key=pre_key,
                pre_jit=pre._step if pre_raw is not None else None,
            )
            op._group_src_channels = group_src
            return op

        feed = src.stream if pre_raw is not None else pre.process(src.stream)
        if budget and ngroups:
            stream = _agg_wave_stream(
                make_op, feed, list(range(ngroups)), int(budget),
                observer=self._observer(), spill_factory=self._make_spiller,
                properties=self.properties,
            )
        else:
            stream = make_op().process(feed)
        return PhysicalPlan(stream, node.outputs)

    def _visit_MarkDistinctNode(self, node: P.MarkDistinctNode) -> PhysicalPlan:
        from trino_tpu.ops.aggregation import MarkDistinctOperator

        src = self.plan(node.source)
        op = MarkDistinctOperator(
            [src.channel(s.name) for s in node.key_symbols]
        )
        return PhysicalPlan(op.process(src.stream), node.outputs)

    def _distinct_preagg(self, node: P.AggregationNode, src: PhysicalPlan) -> PhysicalPlan:
        """DISTINCT aggregates via pre-grouping (reference role: the
        MarkDistinct/pre-aggregation rewrites in AddExchanges/optimizer).
        Supported: every distinct aggregate shares the same argument list and
        non-distinct aggregates are absent."""
        if not supports_uniform_distinct(node):
            raise NotImplementedError("mixed DISTINCT aggregate shapes")
        proj, symbols = build_distinct_dedupe(node, src)
        dedupe = AggregationOperator(
            list(range(len(proj))), [], [e.type for e in proj], mode="single", streaming=True
        )
        pre = FilterProjectOperator(None, proj)
        stream = dedupe.process(pre.process(src.stream))
        return PhysicalPlan(stream, symbols)

    # -- joins ----------------------------------------------------------------

    def _visit_JoinNode(self, node: P.JoinNode) -> PhysicalPlan:
        if node.kind == "cross":
            left = self.plan(node.left)
            right = self.plan(node.right)
            op = NestedLoopJoinOperator(right.types())
            op.set_build(list(right.stream))
            return PhysicalPlan(op.process(left.stream), left.symbols + right.symbols)
        if node.kind == "right":
            flipped = P.JoinNode(
                "left", node.right, node.left,
                [(r, l) for l, r in node.criteria], node.filter, node.distribution,
            )
            out = self._visit_JoinNode(flipped)
            # restore left ++ right symbol order
            order = [out.channel(s.name) for s in node.outputs]
            proj = FilterProjectOperator(
                None, [InputRef(c, out.symbols[c].type) for c in order]
            )
            return PhysicalPlan(proj.process(out.stream), node.outputs)

        from trino_tpu.runtime.memory import (
            ExceededMemoryLimitException,
            batch_bytes,
        )

        build = self.plan(node.right)
        build_batches = list(build.stream)
        if node.kind == "inner":
            # dynamic filtering: build-side key ranges prune the probe scan
            # (registered before the probe subtree is planned, the
            # DynamicFilterService ordering)
            for lsym, rsym in node.criteria:
                rng = _host_minmax(build_batches, build.channel(rsym.name))
                if rng is not None:
                    self.dynamic_filters[lsym.name] = rng
        probe = self.plan(node.left)
        # pipeline parallelism (§2.7(4)): the probe feed starts decoding NOW,
        # overlapping the build side's device-side compaction/indexing.
        # Planned AFTER the build drain so dynamic filters still apply.
        from trino_tpu.runtime.prefetch import eager_prefetch

        probe = PhysicalPlan(eager_prefetch(probe.stream, depth=2), probe.symbols)
        out_symbols = probe.symbols + build.symbols
        probe_keys = [probe.channel(l.name) for l, _ in node.criteria]
        build_keys = [build.channel(r.name) for _, r in node.criteria]
        residual = None
        residual_key = None
        if node.filter is not None:
            combined = PhysicalPlan(iter(()), out_symbols)
            res_expr = combined.rewrite(node.filter)
            residual_key = res_expr.key()

            def residual(batch: Batch, _e=res_expr):
                return ExprCompiler(batch).filter_mask(_e)

        def make_op():
            return HashJoinOperator(
                node.kind,
                probe_keys,
                build_keys,
                build.types(),
                probe_types=probe.types(),
                residual=residual,
                residual_key=residual_key,
            )

        # reserve the dense build footprint BEFORE materializing on device;
        # on budget overflow degrade to hash-partitioned waves (the HBM
        # analog of build-side spill: HashBuilderOperator.startMemoryRevoke
        # + GenericPartitioningSpiller + SpillingJoinProcessor)
        from trino_tpu.runtime import spill as _spill

        ctx = self.memory.child("join_build")
        observer = self._observer()
        from trino_tpu.runtime.memory import batches_bytes

        build_bytes = batches_bytes(build_batches)
        need = 2 * build_bytes  # raw batches + compacted copy
        try:
            ctx.add_bytes(need)
        except ExceededMemoryLimitException:
            n_waves = _spill.wave_count(
                need, self._budget(), self.properties
            )
            spiller = self._make_spiller()
            build_host = device_get_async(list(build_batches))
            build_batches.clear()
            build_side = _spill.partition_side(
                build_host, build_keys, n_waves, spiller, "jb"
            )
            del build_host

            def wave_stream():
                try:
                    probe_host = device_get_async(list(probe.stream))
                    probe_side = _spill.partition_side(
                        probe_host, probe_keys, n_waves, spiller, "jp"
                    )
                    del probe_host
                    yield from _spill.partition_wave_join(
                        make_op, build_side, probe_side, n_waves, ctx,
                        observer,
                    )
                finally:
                    if spiller is not None:
                        spiller.close()

            return PhysicalPlan(wave_stream(), out_symbols)
        op = make_op()
        op.set_build(build_batches)
        if node.kind == "full":
            # full outer tracks build-side matched flags across the whole
            # probe; a mid-stream revoke cannot split that state exactly,
            # so full joins stay non-revocable (waves still cover them on
            # the up-front over-budget path above)
            def stream():
                yield from op.process(probe.stream)
                ctx.close()

            return PhysicalPlan(stream(), out_symbols)

        # register as REVOCABLE (HashBuilderOperator.startMemoryRevoke):
        # under shared-pool pressure — another query reserving, or a pool
        # limit shrunk mid-query — the escalation hook asks this build to
        # spill its partitions and release; the probe loop notices at its
        # next batch and finishes in waves against the spilled build
        holder: dict = {}

        def revoke_spill() -> int:
            # runs on the REQUESTING thread under the handle lock; the
            # owner may be mid-batch against op's device build, so only
            # the raw build batches are copied out here — the owner drops
            # its own device references at its next batch boundary
            spiller = self._make_spiller()
            k = _spill.wave_count(need, self._budget(), self.properties)
            host = device_get_async(list(build_batches))
            holder["side"] = _spill.partition_side(
                host, build_keys, k, spiller, "jb"
            )
            holder["spiller"] = spiller
            holder["k"] = k
            build_batches.clear()
            freed = ctx.reserved
            ctx.set_bytes(0)
            return freed

        handle = _spill.REVOCABLES.register(
            _spill.RevocableOperator("join", ctx, revoke_spill)
        )

        def stream():
            try:
                it = iter(probe.stream)
                for pb in it:
                    if handle.revoked:
                        # build spilled by the revoke tier: drop our device
                        # references, then this batch and the rest of the
                        # probe finish in waves against the spilled build
                        import itertools

                        op.release_build()
                        yield from _revoked_join_remainder(
                            make_op, holder, probe_keys,
                            itertools.chain([pb], it), ctx, observer,
                        )
                        return
                    yield op._join_batch(pb)
                ctx.close()
            finally:
                handle.finish()
                sp = holder.get("spiller")
                if sp is not None:
                    sp.close()

        return PhysicalPlan(stream(), out_symbols)

    # -- memory-pressure join waves (spill analog) ----------------------------

    def _visit_SemiJoinNode(self, node: P.SemiJoinNode) -> PhysicalPlan:
        src = self.plan(node.source)
        filt = self.plan(node.filtering)
        residual = None
        residual_key = None
        if node.filter is not None:
            combined = PhysicalPlan(iter(()), src.symbols + filt.symbols)
            res_expr = combined.rewrite(node.filter)
            residual_key = res_expr.key()

            def residual(batch: Batch, _e=res_expr):
                return ExprCompiler(batch).filter_mask(_e)

        op = SemiJoinOperator(
            src.channel(node.source_key.name),
            filt.channel(node.filtering_key.name),
            filt.types(),
            null_aware=node.null_aware,
            residual=residual,
            residual_key=residual_key,
        )
        op.set_build(list(filt.stream))
        return PhysicalPlan(op.process(src.stream), src.symbols + [node.mark])

    def _visit_WindowNode(self, node: P.WindowNode) -> PhysicalPlan:
        from trino_tpu.ops.window import WindowOperator, WindowSpec

        src = self.plan(node.source)
        part = [src.channel(s.name) for s in node.partition_by]
        order = [
            SortKey(src.channel(s.name), asc, nf)
            for s, asc, nf in node.order_by
        ]
        specs = []
        for out_sym, fn in node.functions:
            arg = None
            if fn.args:
                a0 = fn.args[0]
                arg = src.channel(a0.name)
            default_ch = None
            if fn.default is not None:
                default_ch = src.channel(fn.default.name)
            specs.append(
                WindowSpec(
                    fn.name if fn.name != "count_star" else "count",
                    arg,
                    out_sym.type,
                    offset=fn.offset,
                    default_channel=default_ch,
                    n_buckets=fn.n_buckets_expr or 1,
                    frame=fn.frame,
                    start_off=fn.start_off,
                    end_off=fn.end_off,
                    ignore_nulls=fn.ignore_nulls,
                    sum_bound=getattr(fn, "sum_bound", None),
                )
            )
        budget = self._budget()
        if budget and part:
            stream = _window_wave_stream(
                lambda: WindowOperator(part, order, specs),
                src.stream,
                list(part),
                int(budget),
                observer=self._observer(), spill_factory=self._make_spiller,
                properties=self.properties,
            )
        else:
            # global windows (no PARTITION BY) need every row at once —
            # no partition-disjoint wave exists
            op = WindowOperator(part, order, specs)
            stream = op.process(src.stream)
        return PhysicalPlan(stream, node.outputs)

    # -- ordering / limiting --------------------------------------------------

    def _sort_keys(self, plan: PhysicalPlan, orderings) -> list:
        return [
            SortKey(plan.channel(sym.name), ascending, nulls_first)
            for sym, ascending, nulls_first in orderings
        ]

    def _visit_SortNode(self, node: P.SortNode) -> PhysicalPlan:
        src = self.plan(node.source)
        op = OrderByOperator(
            self._sort_keys(src, node.orderings),
            memory_ctx=self.memory.child("sort"),
            spill_factory=self._make_spiller,
            observer=self._observer(),
        )
        return PhysicalPlan(op.process(src.stream), src.symbols)

    def _visit_TopNNode(self, node: P.TopNNode) -> PhysicalPlan:
        src = self.plan(node.source)
        op = TopNOperator(self._sort_keys(src, node.orderings), node.count)
        return PhysicalPlan(op.process(src.stream), src.symbols)

    def _visit_LimitNode(self, node: P.LimitNode) -> PhysicalPlan:
        src = self.plan(node.source)
        op = LimitOperator(node.count, getattr(node, "offset", 0))
        return PhysicalPlan(op.process(src.stream), src.symbols)

    # -- shape nodes ----------------------------------------------------------

    def _visit_UnionNode(self, node: P.UnionNode) -> PhysicalPlan:
        def stream():
            for child, mapping in zip(node.sources, node.source_symbols):
                sub = self.plan(child)
                exprs = []
                for m, out in zip(mapping, node.symbols):
                    if m.type.name == "unknown":
                        # a NULL-literal branch column: no castable values
                        exprs.append(Literal(None, out.type))
                        continue
                    e: Expr = InputRef(sub.channel(m.name), m.type)
                    if m.type.name != out.type.name:
                        # branch type narrower than the union's unified type
                        # (e.g. decimal cents unioned with double): a real
                        # CAST, not a relabel — decimals must descale
                        e = SpecialForm(Form.CAST, [e], out.type)
                    exprs.append(e)
                proj = FilterProjectOperator(None, exprs)
                yield from proj.process(sub.stream)

        return PhysicalPlan(stream(), node.symbols)

    def _visit_EnforceSingleRowNode(self, node: P.EnforceSingleRowNode) -> PhysicalPlan:
        src = self.plan(node.source)

        def stream():
            total = 0
            emitted = False
            for b in src.stream:
                n = b.num_rows_host()
                total += n
                if total > 1:
                    raise RuntimeError("Scalar sub-query has returned multiple rows")
                if n:
                    emitted = True
                    yield b
            if not emitted:
                import numpy as np

                cols = [
                    Column(
                        np.zeros(1, dtype=s.type.np_dtype),
                        s.type,
                        np.zeros(1, dtype=bool),
                    )
                    for s in src.symbols
                ]
                yield Batch(cols, np.ones(1, dtype=bool))

        return PhysicalPlan(stream(), src.symbols)

    def _visit_ExchangeNode(self, node: P.ExchangeNode) -> PhysicalPlan:
        # single-process execution: exchanges are pass-through; merge
        # exchanges re-sort to restore global order
        src = self.plan(node.source)
        if node.kind == "merge" and node.orderings:
            op = OrderByOperator(self._sort_keys(src, node.orderings))
            return PhysicalPlan(op.process(src.stream), src.symbols)
        return PhysicalPlan(src.stream, src.symbols)

    def _visit_OutputNode(self, node: P.OutputNode) -> PhysicalPlan:
        src = self.plan(node.source)
        if [s.name for s in src.symbols] != [s.name for s in node.symbols]:
            proj = FilterProjectOperator(
                None,
                [InputRef(src.channel(s.name), s.type) for s in node.symbols],
            )
            return PhysicalPlan(proj.process(src.stream), node.symbols)
        return PhysicalPlan(src.stream, node.symbols)


def _revoked_join_remainder(make_op, holder, probe_keys, probe_iter, ctx,
                            observer):
    """Finish a revoked join: the build already sits in spilled partitions
    (holder, written by the revoke callback); the unprocessed remainder of
    the probe stream partitions the same way and the join completes in
    waves.  Probe batches emitted BEFORE the revoke were fully joined
    against the complete build, so the split point is exact."""
    from trino_tpu.runtime import spill as _spill

    probe_host = device_get_async(list(probe_iter))
    probe_side = _spill.partition_side(
        probe_host, probe_keys, holder["k"], holder["spiller"], "jp"
    )
    del probe_host
    yield from _spill.partition_wave_join(
        make_op, holder["side"], probe_side, holder["k"], ctx, observer
    )


def _agg_wave_stream(make_op, feed, key_channels: list, budget: int,
                     observer=None, spill_factory=None, properties=None):
    """Memory-bounded grouped aggregation: group-hash STATE waves.

    Reference role: HashAggregationOperator.startMemoryRevoke:449.  Input
    batches reduce to partial states immediately; when accumulated device
    state crosses a fraction of the budget it SPILLS — through the
    filesystem SPI (runtime/spill.SpillManager npz partitions) when
    `spill_enabled`, host RAM otherwise.  The final merge then runs in
    group-hash waves over the spilled states: hashing by the full group
    key keeps every group inside one wave, so per-wave merges are exact
    and group-disjoint.  Under-budget queries never spill and never copy:
    one device-side merge, identical to the unbudgeted path.

    The accumulating state is registered REVOCABLE: cross-query pressure
    can flush it to the spill tier early instead of killing a query.

    Aggregates without streamable partials (percentile) fall back to
    spooling RAW input and re-feeding each wave — the only shape that
    needs every group row at once.
    """
    import jax

    from trino_tpu.columnar.batch import concat_batches
    from trino_tpu.runtime import spill as _spill
    from trino_tpu.runtime.memory import (
        ExceededMemoryLimitException,
        batch_bytes,
    )

    if observer is None:
        observer = _spill.PressureObserver()
    op = make_op()
    if not op.streaming:
        yield from _agg_raw_wave_stream(
            make_op, op, feed, key_channels, budget, observer,
            spill_factory, properties,
        )
        return
    out_mode = "merge" if op.mode in ("partial", "merge") else "final"
    spill_at = max(budget // 4, 1)
    spiller = None
    spiller_made = False

    def get_spiller():
        nonlocal spiller, spiller_made
        if not spiller_made:
            spiller_made = True
            spiller = spill_factory() if spill_factory is not None else None
        return spiller

    acc: list = [None]  # created on first flush (lazy SpillingAccumulator)
    state = {"device": [], "bytes": 0}

    def flush() -> int:
        """Move accumulated device states to the spill tier; returns bytes
        freed.  Called by the owner (over spill_at) AND by the revoke tier
        (under the handle's reentrant lock)."""
        with handle.lock:
            if not state["device"]:
                return 0
            if acc[0] is None:
                acc[0] = _spill.SpillingAccumulator(get_spiller(), "aggstate")
            acc[0].push_chunk(device_get_async(list(state["device"])))
            state["device"].clear()
            freed = state["bytes"]
            state["bytes"] = 0
        if op.memory_ctx is not None:
            op.memory_ctx.set_bytes(0)
        return freed

    handle = _spill.REVOCABLES.register(
        _spill.RevocableOperator("aggregation", op.memory_ctx, flush)
    )
    seen_any = False
    try:
        for b in feed:
            seen_any = True
            s = op.reduce_batch(b)
            with handle.lock:
                state["device"].append(s)
                state["bytes"] += batch_bytes(s)
                cur = state["bytes"]
            over = cur > spill_at
            if op.memory_ctx is not None:
                try:
                    op.memory_ctx.set_bytes(cur)
                except ExceededMemoryLimitException:
                    over = True  # the reservation tree is the breach signal
                with handle.lock:
                    # a concurrent revoke may have flushed (and released)
                    # between our read of `cur` and the set_bytes above —
                    # re-sync so freed memory is not re-reserved; at most
                    # one revoke can ever fire per handle, so one
                    # correction pass closes the window
                    resync = (
                        state["bytes"] if state["bytes"] != cur else None
                    )
                if resync is not None:
                    try:
                        op.memory_ctx.set_bytes(resync)
                    except ExceededMemoryLimitException:
                        over = True
            if over:
                flush()
        handle.finish()  # merge phase: no longer revocable
        if not seen_any:
            op._acc = []
            yield op.finish()
            if op.memory_ctx is not None:
                op.memory_ctx.close()
            return
        if acc[0] is None:
            # under budget: plain device-side merge, no host round-trip
            device_states = state["device"]
            yield op._combine(
                device_states[0]
                if len(device_states) == 1
                else concat_batches(device_states),
                out_mode,
            )
            if op.memory_ctx is not None:
                op.memory_ctx.close()
            return
        flush()
        total = acc[0].total_bytes
        n_waves = _spill.wave_count(2 * total, budget, properties)
        observer.waves("aggregation", n_waves)
        for wave in range(n_waves):
            # wave selection happens HOST-side by dictionary VALUE hash
            # (state batches carry batch-local dictionaries, so device
            # code hashes would split one group across waves) and each
            # part is compacted before it returns to the device —
            # per-wave footprint is ~total/n_waves, what the budget bought
            parts = [
                jax.device_put(p)
                for p in acc[0].wave_parts(key_channels, n_waves, wave)
            ]
            if not parts:
                continue
            yield op._combine(
                parts[0] if len(parts) == 1 else concat_batches(parts),
                out_mode,
            )
        if op.memory_ctx is not None:
            op.memory_ctx.close()
    finally:
        handle.finish()
        if spiller is not None:
            spiller.close()


def _window_wave_stream(make_op, feed, key_channels: list, budget: int,
                        observer=None, spill_factory=None, properties=None):
    """Memory-bounded window execution: window functions only ever look
    within ONE partition, so hash-partitioning the input by the PARTITION BY
    keys into waves is exact — each wave materializes and sorts only its
    slice on device (reference role: the spill path of WindowOperator.java/
    PagesIndex, reshaped as partition-disjoint waves).  Over-budget input
    stages through the filesystem SPI when `spill_enabled`."""
    import jax

    from trino_tpu.runtime import spill as _spill
    from trino_tpu.runtime.memory import batch_bytes

    if observer is None:
        observer = _spill.PressureObserver()
    acc_dev: list = []
    store = None
    spiller = None
    total = 0
    seen_dicts: set = set()
    try:
        for b in feed:
            # shared dictionaries counted once across the accumulation
            total += batch_bytes(b, _seen_dicts=seen_dicts)
            if store is not None:
                store.push_chunk(device_get_async([b]))
            else:
                acc_dev.append(b)
                if total > budget:
                    spiller = (
                        spill_factory() if spill_factory is not None else None
                    )
                    store = _spill.SpillingAccumulator(spiller, "window")
                    # device memory -> spill tier
                    store.push_chunk(device_get_async(list(acc_dev)))
                    acc_dev.clear()
        if store is None:
            yield from make_op().process(iter(acc_dev))
            return
        n_waves = _spill.wave_count(2 * total, budget, properties)
        observer.waves("window", n_waves)
        for wave in range(n_waves):
            parts = store.wave_parts(key_channels, n_waves, wave)
            if not parts:
                continue
            yield from make_op().process(jax.device_put(p) for p in parts)
    finally:
        if spiller is not None:
            spiller.close()


def _agg_raw_wave_stream(make_op, op, feed, key_channels: list, budget: int,
                         observer=None, spill_factory=None, properties=None):
    """Raw-input waves for non-streamable aggregates (percentile): spool
    input to the spill tier once the budget is breached, then re-feed per
    wave."""
    import jax

    from trino_tpu.runtime import spill as _spill
    from trino_tpu.runtime.memory import ExceededMemoryLimitException

    if observer is None:
        observer = _spill.PressureObserver()
    it = iter(feed)
    spool = []
    over = False
    for b in it:
        spool.append(device_get_async(b))
        try:
            op.push(b)
            if op.state_bytes() > budget:
                over = True
        except ExceededMemoryLimitException:
            over = True  # the reservation tree is the breach signal
        if over:
            break
    if not over:
        yield op.finish()
        if op.memory_ctx is not None:
            op.memory_ctx.close()
        return
    consumed = len(spool)
    spool.extend(device_get_async(list(it)))
    frac = consumed / max(len(spool), 1)
    projected = op.state_bytes() / max(frac, 1e-3)
    n_waves = _spill.wave_count(int(2 * projected), budget, properties)
    if op.memory_ctx is not None:
        op.memory_ctx.close()
    del op  # free the over-budget device state before wave 1
    spiller = spill_factory() if spill_factory is not None else None
    # n_waves is known BEFORE anything is written, so the raw input
    # partitions at write time (one file per wave, each read exactly once)
    # — the state-wave accumulator's k-pass re-read would multiply disk
    # I/O by k over data that is the RAW input, not compacted states
    side = _spill.partition_side(spool, key_channels, n_waves, spiller, "aggraw")
    spool = None
    observer.waves("aggregation", n_waves)
    try:
        for wave in range(n_waves):
            wop = make_op()
            for p in side.load_part(wave):
                wop.push(jax.device_put(p))
            yield wop.finish()
            if wop.memory_ctx is not None:
                wop.memory_ctx.close()
    finally:
        if spiller is not None:
            spiller.close()


def supports_uniform_distinct(node: "P.AggregationNode") -> bool:
    """The DISTINCT shape both _distinct_preagg and the distributed
    repartition path can express: every aggregate DISTINCT over one shared
    argument list, no FILTER clauses (the fragmenter and executor consult
    THIS predicate so plan- and run-time envelopes cannot diverge)."""
    distincts = [a for _, a in node.aggregations if a.distinct]
    return bool(distincts) and (
        len(distincts) == len(node.aggregations)
        and len({tuple(x.key() for x in a.args) for a in distincts}) == 1
        and all(a.filter is None for a in distincts)
    )


def build_distinct_dedupe(node: "P.AggregationNode", src) -> tuple:
    """(projection exprs, output symbols) of the DISTINCT dedupe
    pre-aggregation — group keys then the (uniform) distinct argument
    columns.  The ONE place this layout is decided; used by the local
    planner and the distributed single-stage path."""
    args0 = next(a for _, a in node.aggregations if a.distinct).args
    keys = [src.rewrite(s.ref()) for s in node.group_symbols]
    proj = keys + [src.rewrite(a) for a in args0]
    symbols = list(node.group_symbols) + [
        P.Symbol(a.name, a.type) for a in args0
    ]
    return proj, symbols


def build_agg_inputs(node: "P.AggregationNode", src) -> tuple:
    """(projection exprs, AggSpecs, input types) for an AggregationNode —
    the ONE place the aggregate input layout is decided (group keys first,
    then one computed arg per aggregate, FILTER folded as IF(filter, arg,
    NULL), two-input aggregates consuming two channels).  Shared by the
    local planner and the distributed partial-aggregation path so their
    channel layouts can never diverge.  Reference role: AggregationOperator
    input channels + the mask channel."""
    ngroups = len(node.group_symbols)
    proj: list = [src.rewrite(s.ref()) for s in node.group_symbols]
    specs: list = []
    input_types = [s.type for s in node.group_symbols]
    for out_sym, agg in node.aggregations:
        name = agg.function
        arg = src.rewrite(agg.args[0]) if agg.args else None
        if agg.filter is not None:
            f = src.rewrite(agg.filter)
            if name == "count_star":
                name = "count"
                arg = SpecialForm(
                    Form.IF,
                    [f, Literal(1, T.BIGINT), Literal(None, T.BIGINT)],
                    T.BIGINT,
                )
            else:
                arg = SpecialForm(
                    Form.IF, [f, arg, Literal(None, arg.type)], arg.type
                )
        if arg is None:
            specs.append(AggSpec(name, None, out_sym.type))
            continue
        proj.append(arg)
        input_types.append(arg.type)
        arg2_ch = None
        if len(agg.args) > 1:
            # two-input aggregates (map_agg key/value, covar/corr y/x)
            arg2 = src.rewrite(agg.args[1])
            if agg.filter is not None:
                f2 = src.rewrite(agg.filter)
                arg2 = SpecialForm(
                    Form.IF, [f2, arg2, Literal(None, arg2.type)], arg2.type
                )
            proj.append(arg2)
            input_types.append(arg2.type)
            arg2_ch = ngroups + len(specs_args(specs)) + 1
        specs.append(
            AggSpec(
                name,
                ngroups + len(specs_args(specs)),
                out_sym.type,
                param=getattr(agg, "param", None),
                arg2=arg2_ch,
                # planner range-certificate license (verify.numeric
                # license_decimal_sums): rides the plan node so the local,
                # partial, and merge kernels all read the same proof
                sum_bound=getattr(agg, "sum_bound", None),
            )
        )
    return proj, specs, input_types


def specs_args(specs: list) -> list:
    """Channels already consumed by aggregate args (for layout allocation).
    Two-input aggregates (map_agg) consume two slots."""
    out = []
    for s in specs:
        if s.arg is not None:
            out.append(s)
        if getattr(s, "arg2", None) is not None:
            out.append(s)
    return out


_MINMAX_STEP_CACHE: dict = {}


def _host_minmax(batches, channel: int):
    """(lo, hi) of a materialized column's live+valid values, or None when
    the domain is empty/unfilterable (dictionary codes aren't portable
    across scans).

    The reduction runs ON DEVICE and only three scalars come back per batch
    (packed into one array = one host sync).  Pulling the whole column to
    host — the previous design — costs hundreds of ms per build batch when
    the device sits behind a remote tunnel (~30 MB/s)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    lo = hi = None
    for b in batches:
        c = b.columns[channel]
        if c.dictionary is not None:
            return None
        if c.data.ndim > 1:
            return None  # long-decimal limb planes: no scalar range
        dt = np.dtype(c.data.dtype)
        if dt == np.dtype(bool):
            return None  # boolean join keys: range pruning is pointless
        step = _MINMAX_STEP_CACHE.get(dt.str)
        if step is None:

            def _step(data, live):
                if jnp.issubdtype(data.dtype, jnp.floating):
                    big = jnp.asarray(jnp.inf, data.dtype)
                    small = jnp.asarray(-jnp.inf, data.dtype)
                else:
                    info = jnp.iinfo(data.dtype)
                    big = jnp.asarray(info.max, data.dtype)
                    small = jnp.asarray(info.min, data.dtype)
                lo_ = jnp.min(jnp.where(live, data, big))
                hi_ = jnp.max(jnp.where(live, data, small))
                # any-live flag, NOT a count: a count cast to a narrow key
                # dtype (int8/int16) wraps to 0 at 256/65536 live rows and
                # would silently skip the batch
                n = jnp.any(live).astype(data.dtype)
                return jnp.stack([lo_, hi_, n])

            step = jax.jit(_step)
            _MINMAX_STEP_CACHE[dt.str] = step
        live = b.mask()
        if c.valid is not None:
            live = jnp.logical_and(live, c.valid)
        packed = np.asarray(step(c.data, live))
        if packed[2] == 0:
            continue
        blo, bhi = packed[0], packed[1]
        lo = blo if lo is None else min(lo, blo)
        hi = bhi if hi is None else max(hi, bhi)
    if lo is None:
        return None
    return (lo, hi)


def _range_expr(sym, lo, hi) -> Expr:
    from decimal import Decimal

    from trino_tpu.expr.ir import and_, comparison

    t = sym.type
    if isinstance(t, T.DecimalType):
        lo_v = Decimal(int(lo)) / t.scale_factor
        hi_v = Decimal(int(hi)) / t.scale_factor
    elif t.np_dtype.kind == "f":
        lo_v, hi_v = float(lo), float(hi)
    else:
        lo_v, hi_v = int(lo), int(hi)
    return and_(
        comparison(">=", sym.ref(), Literal(lo_v, t)),
        comparison("<=", sym.ref(), Literal(hi_v, t)),
    )


#: jitted compaction per static output capacity (shape-bucketed)
_COMPACT_CACHE: dict = {}


def _compact_stream(stream):
    import jax

    from trino_tpu.ops.common import next_pow2

    for b in stream:
        n = b.num_rows_host()
        cap = next_pow2(max(n, 1), floor=1024)
        if cap >= b.capacity:
            yield b
            continue
        fn = _COMPACT_CACHE.get(cap)
        if fn is None:
            fn = jax.jit(Batch.compact_device, static_argnames=("out_capacity",))
            _COMPACT_CACHE[cap] = fn
        yield fn(b, out_capacity=cap)
