"""Query lifecycle: state machine, deadlines, cooperative cancellation,
and the low-memory killer.

Reference roles: execution/QueryTracker.java (enforceTimeLimits — the
query_max_run_time / query_max_planning_time sweep), QueryStateMachine
(QUEUED -> RUNNING -> FINISHING -> FINISHED|FAILED|CANCELED, with terminal
states frozen), memory/LowMemoryKiller.java +
TotalReservationLowMemoryKiller (pick the query with the largest
reservation when the pool blocks), and the per-request deadline derivation
of HttpRemoteTask (every RPC timeout bounded by what is left of the query).

Engine mapping: one `QueryContext` per statement, created by
`LocalQueryRunner.execute` and published through a contextvar so deep call
sites (driver loop, SPMD launches, multi-host stage polls, HTTP helpers)
can consult it without threading a handle through every signature.
Cancellation is COOPERATIVE: `check()` is called at fragment boundaries,
between result batches, before each SPMD launch, and inside remote fetch
retries — a canceled or expired query aborts at the next boundary with a
classified error instead of hanging.  Aborts propagate
`RemoteTaskClient.cancel` to every live remote task.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Callable, Optional

# -- error surface ------------------------------------------------------------


class QueryAbortedException(RuntimeError):
    """Base for lifecycle aborts.  Deliberately NOT a ConnectionError /
    TimeoutError subclass: retry machinery must never classify an abort as
    transient and re-run the query past its deadline."""

    #: reference: spi ErrorCode name carried into QueryCompletedEvent
    error_code: str = "ABORTED"


class QueryCanceledException(QueryAbortedException):
    """DELETE /v1/query/{id} or QueryTracker.cancel (USER_ERROR/CANCELED)."""

    error_code = "USER_CANCELED"


class QueryDeadlineExceeded(QueryAbortedException):
    """query_max_run_time / query_max_planning_time expired
    (INSUFFICIENT_RESOURCES / EXCEEDED_TIME_LIMIT)."""

    error_code = "EXCEEDED_TIME_LIMIT"


class QueryQueuedTimeExceeded(QueryAbortedException):
    """query_max_queued_time expired while the query waited for admission
    (reference: QueryTracker.enforceTimeLimits' queued-time sweep /
    EXCEEDED_QUEUED_TIME_LIMIT).  Raised by the dispatcher's admission
    wait, BEFORE the query ever occupies an engine lane."""

    error_code = "EXCEEDED_QUEUED_TIME_LIMIT"


class QueryKilledException(QueryAbortedException):
    """Chosen as the low-memory killer's victim
    (INSUFFICIENT_RESOURCES / CLUSTER_OUT_OF_MEMORY)."""

    error_code = "CLUSTER_OUT_OF_MEMORY"


#: QueryContext.kill reason -> exception class raised at the next check()
_REASON_EXC = {
    "canceled": QueryCanceledException,
    "deadline": QueryDeadlineExceeded,
    "memory": QueryKilledException,
}


# -- task-recovery classification (fault-tolerant execution) -------------------

#: recovery action vocabulary (the {outcome} label of
#: trino_tpu_task_retries_total and the `recovery` decision kind)
RETRY = "retry"
REPLAN = "replan"
FAIL = "fail"

#: per-error-code recovery classification (reference: the retry-type
#: predicate split of EventDrivenFaultTolerantQueryScheduler — worker
#: failures re-run only the lost tasks; user errors are never retried).
#:
#:   retry  — same plan, lost tasks only: the mesh signature the plan was
#:            fragmented for still has live hosts, finished fragments
#:            resume from spooled intermediates, only lost outputs re-run.
#:   replan — the mesh signature truly changed (survivors cannot host the
#:            plan's fragments): re-fragment the query at the shrunk W.
#:   fail   — user/semantic errors: retrying re-raises the same error, so
#:            the classification NEVER retries them.  Unknown codes
#:            default here too — an unclassified error is not evidence of
#:            a lost task.
RECOVERY_CLASSIFICATION = {
    # lost tasks: the work is retryable, the plan is not at fault
    "WORKER_DEATH": RETRY,
    "WORKER_DRAIN": RETRY,
    "TRANSIENT_FETCH": RETRY,
    # the mesh the plan was fragmented for no longer exists
    "MESH_SHRINK_BELOW_REQUIREMENT": REPLAN,
    # user/semantic: retrying cannot change the outcome
    "USER_CANCELED": FAIL,
    "EXCEEDED_TIME_LIMIT": FAIL,
    "EXCEEDED_QUEUED_TIME_LIMIT": FAIL,
    "CLUSTER_OUT_OF_MEMORY": FAIL,
    "ABORTED": FAIL,
    "STAGE_FAILED": FAIL,
    "INTERNAL_ERROR": FAIL,
}


def error_code_of(exc: BaseException) -> str:
    """Classify an exception into the recovery table's error-code
    vocabulary (lifecycle aborts carry their own code; infrastructure
    failures map onto worker-death/drain/transient-fetch)."""
    if isinstance(exc, QueryAbortedException):
        return exc.error_code
    # local import: membership imports retry/metrics at call time itself,
    # and lifecycle must stay importable first
    from trino_tpu.runtime.membership import (
        MeshChangedError,
        WorkerDrainingError,
    )
    from trino_tpu.runtime.retry import StageFailedException

    if isinstance(exc, MeshChangedError):
        if exc.drained and not exc.dead:
            return "WORKER_DRAIN"
        return "WORKER_DEATH"
    if isinstance(exc, StageFailedException):
        return "STAGE_FAILED"
    if isinstance(exc, WorkerDrainingError):
        return "WORKER_DRAIN"
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return "TRANSIENT_FETCH"
    return "INTERNAL_ERROR"


def recovery_action(exc: BaseException) -> str:
    """The classified recovery action for an error (`retry` | `replan` |
    `fail`); unknown codes fail — an unclassified error is never
    retried."""
    return RECOVERY_CLASSIFICATION.get(error_code_of(exc), FAIL)


# -- state machine ------------------------------------------------------------

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHING = "FINISHING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"

TERMINAL_STATES = frozenset({FINISHED, FAILED, CANCELED})

#: legal transitions (reference: execution/QueryState.java's ordering —
#: terminal states are frozen, and the machine never moves backwards)
_TRANSITIONS = {
    QUEUED: {RUNNING, FAILED, CANCELED},
    RUNNING: {FINISHING, FAILED, CANCELED},
    FINISHING: {FINISHED, FAILED, CANCELED},
    FINISHED: set(),
    FAILED: set(),
    CANCELED: set(),
}


class InvalidStateTransition(RuntimeError):
    pass


#: default per-request HTTP timeout when no query deadline bounds it
#: (the old hardcoded 600 s scattered through server/ + remote.py).  These
#: four are now the compiled-in DEFAULTS of the typed config's lifecycle
#: section (trino_tpu/config: lifecycle.request-timeout etc.) — load a
#: config.properties / set TRINO_TPU_LIFECYCLE_* to override them.
DEFAULT_HTTP_TIMEOUT_S = 600.0
#: task submission POST (small body, worker answers immediately)
SUBMIT_TIMEOUT_S = 60.0
#: best-effort task cancel DELETE
CANCEL_TIMEOUT_S = 10.0
#: worker liveness probe GET /v1/info
PROBE_TIMEOUT_S = 5.0


class QueryContext:
    """Per-query lifecycle handle: state machine + deadline + cancellation
    token + registered remote tasks + attached memory contexts."""

    def __init__(
        self,
        query_id: str,
        max_run_time_s: float = 0.0,
        max_planning_time_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.query_id = query_id
        self.clock = clock
        self.created_at = clock()
        self.state = QUEUED
        #: absolute deadlines on the injectable clock (None = unbounded)
        self.deadline = (
            self.created_at + max_run_time_s if max_run_time_s > 0 else None
        )
        self.planning_deadline = (
            self.created_at + max_planning_time_s
            if max_planning_time_s > 0
            else None
        )
        self._lock = threading.Lock()
        self._cancel = threading.Event()
        #: why the token fired: canceled | deadline | memory
        self.kill_reason: Optional[str] = None
        #: human-readable detail surfaced in the raised exception
        self.kill_detail: Optional[str] = None
        #: live RemoteTaskClient handles (multi-host); canceled on abort
        self._tasks: list = []
        #: query-level MemoryContexts reserved on the shared pool; released
        #: when the statement finishes (success OR failure)
        self._memory: list = []
        #: live SpillManagers owned by this query (runtime/spill registers
        #: them at construction): a query killed or canceled mid-wave must
        #: release its spill partitions through the filesystem SPI NOW,
        #: not when the abandoned wave generator happens to be GC'd or the
        #: hours-scale orphan sweep runs
        self._spills: list = []
        # -- per-statement telemetry handles (the lane-safety contract):
        # concurrent engine lanes each resolve THEIR statement's tracer /
        # mesh profile / trace export through this context instead of
        # racing shared runner attributes (runner.last_mesh_profile and
        # runner.last_trace are properties over these)
        #: the statement's SpanTracer (None until execute installs one)
        self.tracer = None
        #: the statement's MeshProfile (distributed executions only)
        self.mesh_profile = None
        #: peak device-memory reservation of the statement's local plan
        self.peak_memory = 0
        #: Chrome-trace JSON exported when the statement finished tracing
        self.trace_json = None
        #: seconds this statement spent waiting on the device time-slice
        #: gate (runtime/dispatcher device_slice, contended acquires only)
        self.gate_wait_s = 0.0
        #: reference to this statement's archived profile artifact
        #: (telemetry/profile_store), set after FINISHING
        self.profile_ref = None
        #: the statement's plan-decision ledger (telemetry/decisions):
        #: planner rules and runtime branches record choices here via the
        #: contextvar, the runner joins outcomes + stamps hindsight before
        #: archiving (same lane-safety contract as the tracer)
        self.decisions = None

    # -- state machine --------------------------------------------------------

    def transition(self, to: str) -> None:
        with self._lock:
            self._transition_locked(to)

    def _transition_locked(self, to: str) -> None:  # lint: allow(unguarded-state)
        """Caller holds self._lock."""
        if to not in _TRANSITIONS.get(self.state, set()):
            raise InvalidStateTransition(
                f"query {self.query_id}: illegal transition "
                f"{self.state} -> {to}"
            )
        self.state = to

    @property
    def done(self) -> bool:
        with self._lock:
            return self.state in TERMINAL_STATES

    def begin(self) -> None:
        self.transition(RUNNING)

    def finishing(self) -> None:
        # check-then-transition is atomic: a concurrent fail() cannot slip
        # between the read and the write (the unguarded-state race the
        # concurrency analyzer flagged — finish() could resurrect a FAILED
        # query to FINISHED)
        with self._lock:
            if self.state == RUNNING:
                self._transition_locked(FINISHING)

    def finish(self) -> None:
        with self._lock:
            if self.state == RUNNING:
                # short statements (SET SESSION) may finish without FINISHING
                self._transition_locked(FINISHING)
            if self.state == FINISHING:
                self._transition_locked(FINISHED)
            elif self.state == QUEUED:
                self.state = FINISHED

    def fail(self, exc: BaseException) -> str:
        """Move to the terminal failure state for `exc`; returns the event
        state string (CANCELED for user cancels, FAILED otherwise)."""
        state = CANCELED if isinstance(exc, QueryCanceledException) else FAILED
        with self._lock:
            if self.state not in TERMINAL_STATES:
                self.state = state
        self.cancel_tasks()
        return state

    # -- cancellation token ---------------------------------------------------

    def cancel(self, detail: Optional[str] = None) -> None:
        """User-initiated cancel (DELETE /v1/query/{id})."""
        self.kill(reason="canceled", detail=detail or "canceled by user")

    def kill(self, reason: str, detail: Optional[str] = None) -> None:
        """Arm the token; the query aborts at its next cooperative check.
        First reason wins (a memory kill is not overwritten by a later
        deadline sweep)."""
        with self._lock:
            if self.kill_reason is None:
                self.kill_reason = reason
                self.kill_detail = detail
        self._cancel.set()
        self.cancel_tasks()

    @property
    def canceled(self) -> bool:
        return self._cancel.is_set()

    def remaining_s(self) -> Optional[float]:
        """Seconds left until the run deadline (None = unbounded)."""
        if self.deadline is None:
            return None
        return self.deadline - self.clock()

    def check(self) -> None:
        """Cooperative cancellation point: raises the classified abort when
        the token fired or the deadline passed.  Cheap (one Event.is_set +
        one clock read) — safe at per-batch / per-launch granularity."""
        if self._cancel.is_set():
            with self._lock:  # reason/detail are written under the lock
                reason, detail = self.kill_reason, self.kill_detail
            exc = _REASON_EXC.get(reason, QueryCanceledException)
            raise exc(
                f"query {self.query_id} {detail or reason or 'canceled'}"
            )
        if self.deadline is not None and self.clock() > self.deadline:
            # arm through kill() so live remote tasks get their cancel
            self.kill(
                "deadline",
                detail=(
                    f"exceeded query_max_run_time "
                    f"({self.deadline - self.created_at:.3f}s)"
                ),
            )
            raise QueryDeadlineExceeded(
                f"query {self.query_id} exceeded query_max_run_time "
                f"({self.deadline - self.created_at:.3f}s)"
            )

    def check_planning(self) -> None:
        """Planning-phase deadline (query_max_planning_time); also enforces
        the run deadline and the token."""
        self.check()
        if (
            self.planning_deadline is not None
            and self.clock() > self.planning_deadline
        ):
            self.kill(
                "deadline",
                detail=(
                    f"exceeded query_max_planning_time "
                    f"({self.planning_deadline - self.created_at:.3f}s)"
                ),
            )
            raise QueryDeadlineExceeded(
                f"query {self.query_id} exceeded query_max_planning_time "
                f"({self.planning_deadline - self.created_at:.3f}s)"
            )

    def http_timeout(self, default: float = DEFAULT_HTTP_TIMEOUT_S) -> float:
        """Per-request timeout derived from the deadline: never wait on a
        socket longer than the query has left to live.  Raises when the
        deadline already passed (the request would be pointless)."""
        self.check()
        rem = self.remaining_s()
        if rem is None:
            return default
        return max(min(default, rem), 0.001)

    # -- abort propagation ----------------------------------------------------

    def register_task(self, client) -> None:
        """Track a live remote task so aborts propagate its cancel."""
        with self._lock:
            self._tasks.append(client)

    def cancel_tasks(self) -> None:
        """Best-effort RemoteTaskClient.cancel on every registered task
        (reference: SqlStageExecution cancel fan-out on query failure)."""
        with self._lock:
            tasks, self._tasks = self._tasks, []
        for t in tasks:
            try:
                t.cancel()
            except Exception:
                pass

    # -- memory ---------------------------------------------------------------

    def attach_memory(self, ctx) -> None:
        with self._lock:
            self._memory.append(ctx)

    def memory_reserved(self) -> int:
        with self._lock:
            return sum(m.reserved for m in self._memory)

    def release_memory(self) -> None:
        with self._lock:
            mem, self._memory = self._memory, []
        for m in mem:
            try:
                m.force_release()
            except Exception:
                pass

    # -- device-gate accounting -----------------------------------------------

    def note_gate_wait(self, wait_s: float) -> None:
        """Fold one contended device-gate wait into this query's total
        (called by dispatcher._DeviceSlice on the contended path only; a
        statement's steps run on one thread at a time, the lock guards
        against an overlapping EXPLAIN-ANALYZE reader)."""
        with self._lock:
            self.gate_wait_s += wait_s

    # -- spill ----------------------------------------------------------------

    def register_spill(self, spiller) -> None:
        """Track a live SpillManager so aborts delete its partitions."""
        with self._lock:
            self._spills.append(spiller)

    def unregister_spill(self, spiller) -> None:
        with self._lock:
            if spiller in self._spills:
                self._spills.remove(spiller)

    def release_spills(self) -> None:
        """Close every still-open SpillManager (statement end, success OR
        abort): partitions delete through the filesystem SPI
        (`delete_recursive` for owned spill dirs).  Close is idempotent,
        so a wave loop's own finally running later is harmless."""
        with self._lock:
            spills, self._spills = self._spills, []
        for s in spills:
            try:
                s.close()
            except Exception:
                pass


# -- current-query contextvar -------------------------------------------------

_CURRENT: "contextvars.ContextVar[Optional[QueryContext]]" = (
    contextvars.ContextVar("trino_tpu_current_query", default=None)
)


def current_query() -> Optional[QueryContext]:
    return _CURRENT.get()


def set_current(ctx: Optional[QueryContext]):
    """Install `ctx` as the executing query; returns the reset token."""
    return _CURRENT.set(ctx)


def reset_current(token) -> None:
    _CURRENT.reset(token)


def check_current() -> None:
    """Cooperative cancellation point for call sites without a handle."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.check()


def check_current_planning() -> None:
    """Planning-phase cancellation point (query_max_planning_time)."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.check_planning()


def request_timeout(default: Optional[float] = None) -> float:
    """HTTP timeout for the executing query (the lifecycle deadline helper
    the raw-http-timeout lint rule routes call sites through): bounded by
    the query's remaining run time, `default` when no query or no
    deadline.  `default=None` reads the typed config's
    `lifecycle.request-timeout` (trino_tpu/config) — the old hardcoded
    600 s is now just that knob's compiled-in default."""
    if default is None:
        from trino_tpu.config import get_config

        default = get_config().lifecycle.request_timeout_s
    ctx = _CURRENT.get()
    if ctx is None:
        return default
    return ctx.http_timeout(default)


def register_task(client) -> None:
    """Attach a remote task to the executing query (no-op without one)."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.register_task(client)


def register_spill(spiller) -> None:
    """Attach a SpillManager to the executing query/task (no-op without
    one): its partitions are released at statement end even when the wave
    generator that owns it is abandoned mid-stream by an abort."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.register_spill(spiller)


# -- dispatcher admission context ---------------------------------------------

#: the resource-group memory sub-pool the executing query was admitted
#: under (runtime/dispatcher sets it around each admitted run): when set,
#: query_memory_context parents query reservations under the GROUP node so
#: the group's memory_limit_bytes bounds them
_GROUP_MEMORY: "contextvars.ContextVar" = contextvars.ContextVar(
    "trino_tpu_group_memory", default=None
)

#: (group name, queued seconds) of the executing query's admission — the
#: tracer's queue span and EXPLAIN ANALYZE read it
_ADMISSION: "contextvars.ContextVar" = contextvars.ContextVar(
    "trino_tpu_admission", default=None
)


def set_group_memory(ctx):
    return _GROUP_MEMORY.set(ctx)


def reset_group_memory(token) -> None:
    _GROUP_MEMORY.reset(token)


def current_group_memory():
    return _GROUP_MEMORY.get()


def set_admission_info(info):
    """info = (group name, queued seconds)."""
    return _ADMISSION.set(info)


def reset_admission_info(token) -> None:
    _ADMISSION.reset(token)


def current_admission():
    return _ADMISSION.get()


#: engine-lane index of the executing statement (dispatcher sets it around
#: each admitted run); the device-gate occupancy gauge labels holds by it.
#: Default 0: undispatched executions (tests, dbapi, prewarm) are lane 0.
_LANE: "contextvars.ContextVar[int]" = contextvars.ContextVar(
    "trino_tpu_lane", default=0
)


def set_lane(index: int):
    return _LANE.set(index)


def reset_lane(token) -> None:
    _LANE.reset(token)


def current_lane() -> int:
    return _LANE.get()


def note_gate_wait(wait_s: float) -> None:
    """Attribute a contended device-gate wait to the executing query
    (no-op without one — e.g. a bare planner test taking the gate)."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.note_gate_wait(wait_s)


# -- tracker ------------------------------------------------------------------


class QueryTracker:
    """Live-query registry (reference: execution/QueryTracker.java).  One
    per runner; DELETE /v1/query/{id} resolves through it.  Canceling an id
    that has not registered yet pre-cancels it (cancel-while-queued)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._live: dict[str, QueryContext] = {}
        self._precanceled: set[str] = set()

    def create(self, query_id: str, properties=None) -> QueryContext:
        max_run = max_plan = 0.0
        if properties is not None:
            try:
                max_run = float(properties.get("query_max_run_time"))
                max_plan = float(properties.get("query_max_planning_time"))
            except KeyError:  # pragma: no cover - older property sets
                pass
        ctx = QueryContext(
            query_id,
            max_run_time_s=max_run,
            max_planning_time_s=max_plan,
            clock=self.clock,
        )
        with self._lock:
            self._live[query_id] = ctx
            pre = query_id in self._precanceled
            self._precanceled.discard(query_id)
        if pre:
            ctx.cancel("canceled before execution started")
        return ctx

    def get(self, query_id: str) -> Optional[QueryContext]:
        with self._lock:
            return self._live.get(query_id)

    def live(self) -> list:
        with self._lock:
            return list(self._live.values())

    def cancel(self, query_id: str) -> bool:
        """True when a live query was canceled; unknown ids pre-cancel (the
        query may be queued and not yet registered)."""
        with self._lock:
            ctx = self._live.get(query_id)
            if ctx is None:
                self._precanceled.add(query_id)
        if ctx is None:
            return False
        ctx.cancel()
        return True

    def remove(self, ctx: QueryContext) -> None:
        with self._lock:
            if self._live.get(ctx.query_id) is ctx:
                del self._live[ctx.query_id]


# -- low-memory killer --------------------------------------------------------


class LowMemoryKiller:
    """TotalReservationLowMemoryKiller analog: when a reservation would
    exceed the shared pool, kill the query holding the LARGEST reservation
    — never the reserving one while another query holds more — reclaim its
    accounting, and let the blocked reservation retry.  The victim aborts
    at its next cooperative check with CLUSTER_OUT_OF_MEMORY."""

    def __call__(self, pool_root, requesting, delta: int) -> bool:
        """memory.MemoryContext on_exceeded hook: True = freed something,
        retry the reservation; False = raise to the requester."""
        req_query = requesting.query_root()
        candidates = [
            q
            for q in getattr(pool_root, "query_children", ())
            if q.reserved > 0
        ]
        if not candidates:
            return False
        victim = max(candidates, key=lambda q: q.reserved)
        if victim is req_query:
            # the requester already holds the largest reservation: failing
            # its reservation IS the kill (never shoot a smaller bystander)
            return False
        owner = getattr(victim, "owner", None)
        from trino_tpu.telemetry.metrics import memory_kills_counter

        memory_kills_counter().inc()
        if owner is not None:
            owner.kill(
                "memory",
                detail=(
                    f"killed by the low-memory killer: largest reservation "
                    f"({victim.reserved} bytes) when "
                    f"{requesting.name} requested {delta} more"
                ),
            )
        victim.force_release()
        return True


#: process-wide device-memory pool shared by all queries in this process
#: (reference: memory/MemoryPool.java's GENERAL pool).  Unlimited by
#: default — set_memory_pool_limit arms the low-memory killer.
_GLOBAL_POOL = None
_POOL_LOCK = threading.Lock()


def memory_pool():
    """The process memory pool with the escalation hook installed: the
    revoke tier (runtime/spill.MemoryEscalation — the largest registered
    wave-capable operator spills and releases) runs first, the low-memory
    killer stays the last resort with its victim choice unchanged."""
    global _GLOBAL_POOL
    with _POOL_LOCK:
        if _GLOBAL_POOL is None:
            from trino_tpu.runtime.memory import MemoryPool
            from trino_tpu.runtime.spill import MemoryEscalation

            _GLOBAL_POOL = MemoryPool()
            _GLOBAL_POOL.root.on_exceeded = MemoryEscalation(LowMemoryKiller())
        return _GLOBAL_POOL


def set_memory_pool_limit(limit_bytes: int) -> None:
    """Arm (limit > 0) or disarm (0) the shared pool limit."""
    memory_pool().root.limit_bytes = int(limit_bytes)


def query_memory_context(limit_bytes: int = 0):
    """Per-query memory context for the local execution planner: on the
    SHARED pool (killer-visible, released by the runner at statement end)
    when a query is executing, else a private throwaway pool (direct
    planner construction in tests / worker tasks).

    When the query was admitted through a resource group with a memory
    limit (dispatcher sets the group sub-pool contextvar), the query node
    parents under the GROUP node: the group limit bounds the reservation
    (spill.effective_budget sees it on the ancestor walk, so waves plan
    against it) and a breach escalates within the group only.  The node
    registers as a victim candidate on BOTH the group and the pool root —
    group-limit escalation is group-scoped, cluster pressure still sees
    every query."""
    ctx = current_query()
    if ctx is None:
        from trino_tpu.runtime.memory import MemoryPool

        return MemoryPool().query_context("query", limit_bytes)
    pool = memory_pool()
    group_ctx = current_group_memory()
    if group_ctx is None:
        mem = pool.query_context(ctx.query_id, limit_bytes)
    else:
        mem = group_ctx.child(f"query:{ctx.query_id}")
        mem.limit_bytes = limit_bytes
        mem.is_query_root = True
        with pool.root._lock:
            group_ctx.query_children.append(mem)
            pool.root.query_children.append(mem)
    mem.owner = ctx
    ctx.attach_memory(mem)
    return mem
