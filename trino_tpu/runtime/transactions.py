"""Transaction manager (reference: transaction/InMemoryTransactionManager
.java — per-connector isolation contexts created at BEGIN, committed or
aborted atomically per connector).

Write-capable connectors are host-side replace-on-write stores, so isolation
is snapshot/restore — taken LAZILY per written table at first write inside
the transaction (the reference's ConnectorTransactionHandle created on first
use).  ROLLBACK restores only tables this transaction wrote, so concurrent
autocommit writes to OTHER tables survive an unrelated rollback.  Write-write
conflicts on the SAME table between a transaction and concurrent autocommit
statements are not detected (last writer wins) — the reference's
READ_UNCOMMITTED-adjacent behavior for in-memory catalogs, documented here.
"""

from __future__ import annotations

from typing import Optional


class TransactionError(RuntimeError):
    pass


_MISSING = object()  # table did not exist at first write


class TransactionManager:
    def __init__(self, catalogs):
        self.catalogs = catalogs
        self._active = False
        #: (catalog, schema, table) -> pre-write snapshot (or _MISSING)
        self._table_snaps: Optional[dict] = None
        #: catalog -> whole-store snapshot (fallback for connectors without
        #: table-granular snapshot support)
        self._catalog_snaps: Optional[dict] = None

    @property
    def active(self) -> bool:
        return self._active

    def begin(self) -> None:
        if self._active:
            raise TransactionError("transaction already in progress")
        self._active = True
        self._table_snaps = {}
        self._catalog_snaps = {}

    def notify_write(self, catalog: str, schema: str, table: str) -> None:
        """Called by the engine BEFORE any DDL/DML mutation.  First write to
        a table inside the transaction snapshots just that table."""
        if not self._active:
            return
        conn = self.catalogs.get(catalog)
        if not conn.supports_writes():
            return
        key = (catalog, schema, table)
        if key in self._table_snaps or catalog in self._catalog_snaps:
            return
        snap_table = getattr(conn, "snapshot_table", None)
        if snap_table is not None:
            self._table_snaps[key] = snap_table(schema, table)
        elif getattr(conn, "snapshot", None) is not None:
            self._catalog_snaps[catalog] = conn.snapshot()

    def commit(self) -> None:
        if not self._active:
            raise TransactionError("no transaction in progress")
        self._active = False
        self._table_snaps = None
        self._catalog_snaps = None

    def rollback(self) -> None:
        if not self._active:
            raise TransactionError("no transaction in progress")
        for (catalog, schema, table), snap in self._table_snaps.items():
            conn = self.catalogs.get(catalog)
            conn.restore_table(schema, table, snap)
        for catalog, snap in self._catalog_snaps.items():
            self.catalogs.get(catalog).restore(snap)
        self._active = False
        self._table_snaps = None
        self._catalog_snaps = None


MISSING = _MISSING
