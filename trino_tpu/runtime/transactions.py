"""Transaction manager (reference: transaction/InMemoryTransactionManager
.java — per-connector isolation contexts created at BEGIN, committed or
aborted atomically per connector).

The engine's write-capable connectors are host-side stores, so transaction
isolation is snapshot/restore: BEGIN snapshots every write-capable catalog,
ROLLBACK restores the snapshots, COMMIT discards them.  Connector data
structures are replace-on-write (appends build new column arrays), so a
shallow store snapshot is sufficient and O(tables)."""

from __future__ import annotations

from typing import Optional


class TransactionError(RuntimeError):
    pass


class TransactionManager:
    def __init__(self, catalogs):
        self.catalogs = catalogs
        self._snapshots: Optional[dict] = None

    @property
    def active(self) -> bool:
        return self._snapshots is not None

    def begin(self) -> None:
        if self.active:
            raise TransactionError("transaction already in progress")
        snaps = {}
        for name in self.catalogs.names():
            conn = self.catalogs.get(name)
            snap = getattr(conn, "snapshot", None)
            if snap is not None and conn.supports_writes():
                snaps[name] = conn.snapshot()
        self._snapshots = snaps

    def commit(self) -> None:
        if not self.active:
            raise TransactionError("no transaction in progress")
        self._snapshots = None

    def rollback(self) -> None:
        if not self.active:
            raise TransactionError("no transaction in progress")
        for name, snap in self._snapshots.items():
            self.catalogs.get(name).restore(snap)
        self._snapshots = None
