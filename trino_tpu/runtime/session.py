"""Session properties (reference: Session.java + SystemSessionProperties.java
— the ~200-knob session-level configuration surface, reduced to the knobs
this engine actually reads).  SET SESSION mutates these per connection."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class PropertyMetadata:
    name: str
    description: str
    type: type
    default: Any


SESSION_PROPERTIES: dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        PropertyMetadata(
            "target_splits", "connector splits per table scan", int, 4
        ),
        PropertyMetadata(
            "page_rows", "max rows per scan page (device batch size)", int, 1 << 20
        ),
        PropertyMetadata(
            "broadcast_join_rows",
            "build sides estimated at or below this are broadcast",
            int,
            50_000,
        ),
        PropertyMetadata(
            "join_distribution_type",
            "AUTOMATIC | BROADCAST | PARTITIONED",
            str,
            "AUTOMATIC",
        ),
        PropertyMetadata(
            "agg_fold_batches",
            "partial-aggregation states folded after this many batches",
            int,
            8,
        ),
        PropertyMetadata(
            "query_max_memory_bytes",
            "per-query device memory budget (0 = unlimited)",
            int,
            0,
        ),
        PropertyMetadata(
            "query_max_memory",
            "per-query device memory budget in bytes (0 = unlimited; the "
            "reservation ceiling blocking operators check before "
            "materializing — exceeding it degrades to partition-wave "
            "execution with filesystem-SPI spill instead of failing; "
            "reference: SystemSessionProperties QUERY_MAX_MEMORY)",
            int,
            0,
        ),
        PropertyMetadata(
            "spill_enabled",
            "spill non-resident partition-wave data host-side through the "
            "filesystem SPI (false = waves stage in host RAM only; "
            "reference: SystemSessionProperties SPILL_ENABLED)",
            bool,
            True,
        ),
        PropertyMetadata(
            "memory_wave_partitions",
            "override the partition-wave fan-out k under memory pressure "
            "(0 = auto: next_pow2(need / budget))",
            int,
            0,
        ),
        PropertyMetadata(
            "query_max_run_time",
            "wall-clock deadline for a whole statement in seconds; the "
            "query aborts with EXCEEDED_TIME_LIMIT at its next cooperative "
            "check (0 = unbounded; reference: QueryTracker.enforceTimeLimits)",
            float,
            0.0,
        ),
        PropertyMetadata(
            "query_max_planning_time",
            "wall-clock deadline for analysis + optimization in seconds "
            "(0 = unbounded)",
            float,
            0.0,
        ),
        PropertyMetadata(
            "query_max_queued_time",
            "wall-clock bound on admission-queue wait in seconds; a query "
            "still queued past it fails with EXCEEDED_QUEUED_TIME_LIMIT "
            "without ever occupying an engine lane (0 = unbounded; "
            "reference: QueryTracker's queued-time sweep)",
            float,
            0.0,
        ),
        PropertyMetadata(
            "retry_policy",
            "NONE | QUERY (re-execute the query) | TASK (per-stage retry "
            "with spooled intermediates)",
            str,
            "NONE",
        ),
        PropertyMetadata(
            "fault_tolerant_execution",
            "spool completed fragment outputs through the filesystem SPI "
            "keyed by (query_id, fragment_id, attempt_id) so a mid-query "
            "worker death resumes from spooled intermediates: only "
            "fragments whose outputs are lost re-run, duplicate attempt "
            "outputs are deduplicated at the consumer (reference: "
            "RetryPolicy.TASK + DeduplicatingDirectExchangeBuffer; false "
            "= today's behavior, retry_policy alone decides)",
            bool,
            False,
        ),
        PropertyMetadata(
            "scan_cache",
            "serve immutable splits from the host/device buffer pool",
            bool,
            True,
        ),
        PropertyMetadata(
            "scan_prefetch_depth",
            "scan batches decoded+transferred ahead of compute (0 = off)",
            int,
            2,
        ),
        PropertyMetadata(
            "profile_dir",
            "write an XLA/jax profiler trace of each query to this "
            "directory (device kernel times; '' = off)",
            str,
            "",
        ),
        PropertyMetadata(
            "task_concurrency",
            "parallel split readers per table scan (local exchange width; "
            "reference: SystemSessionProperties TASK_CONCURRENCY)",
            int,
            4,
        ),
        PropertyMetadata(
            "writer_count",
            "parallel page-building writer threads for INSERT/CTAS "
            "(reference: scaled writers / task_writer_count)",
            int,
            4,
        ),
        PropertyMetadata(
            "verify_plan",
            "plan sanity-checker enforcement: strict (raise PlanViolation) "
            "| warn | off | default (strict under pytest, warn elsewhere)",
            str,
            "default",
        ),
        PropertyMetadata(
            "colocated_join",
            "use table layouts / derived partitioning to elide exchanges "
            "(co-partitioned joins, single-stage aggregations)",
            bool,
            True,
        ),
        PropertyMetadata(
            "join_speculative_capacity",
            "speculative join output capacity: on | off | <initial pow2 "
            "cap override> (off = block on the match-count host sync)",
            str,
            "on",
        ),
        PropertyMetadata(
            "join_capacity_license",
            "honor capacity certificates (verify.capacity): proven joins "
            "compile at the certified fixed capacity with zero runtime "
            "sizing (false = always run the speculative/sizing path)",
            bool,
            True,
        ),
        PropertyMetadata(
            "table_layouts",
            "declared hash-bucketed layouts for generated tables: "
            "'catalog.schema.table:col1+col2:bucket_count', comma-separated",
            str,
            "",
        ),
        PropertyMetadata(
            "global_dictionaries",
            "let plans lean on the global dictionary service "
            "(runtime/dictionary_service): varchar join/group keys whose "
            "two sides share one versioned mesh-wide code assignment "
            "co-locate and elide exchanges like integer keys (false = "
            "producer-local codes only; always sound, just more exchanges)",
            bool,
            True,
        ),
        PropertyMetadata(
            "query_trace",
            "per-query span tracing from admission through SPMD launches "
            "(runner.last_trace / EXPLAIN ANALYZE VERBOSE / "
            "GET /v1/query/{id}/trace; false = zero-overhead off)",
            bool,
            True,
        ),
        PropertyMetadata(
            "decision_regret_ratio",
            "hindsight threshold for the plan-decision ledger "
            "(telemetry/decisions): a decision is stamped 'regret' when "
            "its measured cost exceeds this multiple of the estimated "
            "cost of the alternative it rejected",
            float,
            2.0,
        ),
        PropertyMetadata(
            "decision_regret_min_bytes",
            "byte floor below which the decision ledger never flags "
            "regret (tiny broadcasts are noise, not mistakes)",
            int,
            1 << 20,
        ),
        PropertyMetadata(
            "pallas_agg",
            "use the Pallas MXU one-hot-matmul kernel for eligible "
            "small-domain float aggregations",
            bool,
            False,
        ),
        PropertyMetadata(
            "pallas_probe",
            "use the Pallas blocked binary-search gather-probe kernel for "
            "the join inner loop (single-plane integer keys; falls back to "
            "the XLA probe for limb-coded keys)",
            bool,
            False,
        ),
    ]
}


class SessionProperties:
    def __init__(self):
        self._values: dict[str, Any] = {}

    def get(self, name: str):
        meta = SESSION_PROPERTIES.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        return self._values.get(name, meta.default)

    def set(self, name: str, value) -> None:
        meta = SESSION_PROPERTIES.get(name)
        if meta is None:
            raise KeyError(f"unknown session property: {name}")
        try:
            self._values[name] = meta.type(value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad value for {name}: {value!r}") from e

    def items(self):
        for name, meta in SESSION_PROPERTIES.items():
            yield name, self._values.get(name, meta.default), meta


#: the executing statement's identity, set by the runner around dispatch
#: (reference: Session.getUser() — threaded as a contextvar because the
#: expression analyzer has no session handle)
import contextvars

CURRENT_USER: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "trino_tpu_current_user", default="user"
)
