"""Elastic cluster membership: heartbeat failure detection, drain, grow.

Reference roles: failuredetector/HeartbeatFailureDetector.java:78 (the
coordinator pings workers; consecutive silence marks them failed),
server/GracefulShutdownHandler.java (SURVEY §5.3: drain running tasks,
refuse new ones, then exit), and DiscoveryNodeManager (workers announce
themselves; the scheduler consults the live set per query).

PR 5 made individual queries stoppable; this module makes the CLUSTER
mutable:

  * `ClusterMembership` — the coordinator's worker registry.  Every worker
    is ACTIVE, DRAINING, or DEAD; `active_workers()` is the set the NEXT
    query's mesh is planned against (a membership change never mutates a
    running query's mesh — the running query re-plans or completes).
    Transitions bump `trino_tpu_membership_events_total{kind}` and the
    per-worker `trino_tpu_worker_alive` gauge, and the registry feeds
    `system.runtime.nodes`.
  * `HeartbeatDetector` — periodic worker probes with an injectable clock
    and prober.  Consecutive misses past `heartbeat.miss-threshold` declare
    the worker DEAD and trip its PR 5 circuit breaker; a DEAD worker stays
    DEAD until it explicitly re-registers (the grow path), so a flapping
    worker can never oscillate ACTIVE<->DEAD inside one probe window.
  * `MeshChangedError` — raised by the multi-host scheduler when a worker
    in the CURRENT query's mesh is discovered dead (connection refused) or
    draining (503 REFUSED semantics): the runner marks membership, then
    re-plans the query's remaining fragments against the SHRUNK worker set
    (W-1) instead of retrying forever against a corpse.  Spooled/pull
    exchanges make the replay deterministic.

Everything time-related is injectable so the detector state machine runs in
tier-1 on a deterministic clock with zero sleeps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

# -- worker states -------------------------------------------------------------

ACTIVE = "ACTIVE"
DRAINING = "DRAINING"
DEAD = "DEAD"


class MeshChangedError(RuntimeError):
    """The executing query's worker set changed under it (death or drain):
    re-plan the remaining fragments against the new set.  Deliberately NOT
    a ConnectionError: retry machinery must never absorb a mesh change."""

    def __init__(self, dead=(), drained=()):
        self.dead = tuple(dead)
        self.drained = tuple(drained)
        super().__init__(
            f"mesh changed: dead={list(self.dead)} drained={list(self.drained)}"
        )


class WorkerDrainingError(ConnectionRefusedError):
    """A draining worker refused a task submission (HTTP 503).  Subclasses
    ConnectionRefusedError ON PURPOSE: the PR 5 retry logic already treats
    REFUSED as 'do not retry this worker' — drain refusals ride the same
    classification, they just must not feed the breaker (the worker is
    healthy, it is leaving)."""


@dataclass
class WorkerEntry:
    worker_id: str
    state: str = ACTIVE
    #: clock() of the last successful heartbeat/probe
    last_heartbeat: float = 0.0
    #: consecutive failed probes (reset by any success while not DEAD)
    misses: int = 0
    registered_at: float = 0.0


class ClusterMembership:
    """Coordinator-side worker registry (DiscoveryNodeManager role).

    State machine per worker:

        (register) -> ACTIVE -(drain)-> DRAINING -(exit/miss)-> DEAD
                        |                                        ^
                        +------------- (misses >= threshold) ----+
        DEAD -(register again)-> ACTIVE   ("rejoin": the grow path)

    DEAD is sticky: only an explicit `register` resurrects a worker, so
    probe flaps cannot oscillate the state inside a probe window."""

    def __init__(self, worker_ids=(), clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._workers: dict[str, WorkerEntry] = {}
        for w in worker_ids:
            self.register(w)

    # -- transitions ----------------------------------------------------------

    def register(self, worker_id: str) -> WorkerEntry:
        """A worker announces itself: new workers join, DEAD or DRAINING
        workers rejoin (registration is an explicit grow intent — a
        drained-for-maintenance worker that restarts must be able to come
        back).  Either way it serves the NEXT query's mesh, never a
        running one (schedulers snapshot active_workers per query)."""
        now = self.clock()
        with self._lock:
            e = self._workers.get(worker_id)
            if e is None:
                e = WorkerEntry(worker_id, ACTIVE, now, 0, now)
                self._workers[worker_id] = e
                kind = "join"
            elif e.state in (DEAD, DRAINING):
                e.state = ACTIVE
                e.misses = 0
                e.last_heartbeat = now
                kind = "rejoin"
            else:
                e.last_heartbeat = now
                return e  # already a member: a no-op announce
        self._event(kind)
        self._set_alive(worker_id, 1)
        if kind == "rejoin":
            # fresh start for its breaker too: failure history belongs to
            # the dead incarnation
            from trino_tpu.runtime.retry import BREAKERS

            BREAKERS.get(worker_id).record_success()
        return e

    def drain(self, worker_id: str) -> bool:
        """Mark a worker DRAINING: it finishes running tasks and refuses
        new ones; the next query's mesh excludes it."""
        with self._lock:
            e = self._workers.get(worker_id)
            if e is None or e.state != ACTIVE:
                return False
            e.state = DRAINING
        self._event("drain")
        return True

    def mark_dead(self, worker_id: str, trip_breaker: bool = True) -> bool:
        """Declare a worker DEAD (detector threshold or scheduler evidence);
        trips its circuit breaker OPEN so nothing routes to the corpse —
        EXCEPT when the worker was DRAINING: its exit is the drain
        completing by choice, recorded as death without a breaker trip (the
        breaker narrates failures, not retirements)."""
        with self._lock:
            e = self._workers.get(worker_id)
            if e is None:
                e = WorkerEntry(worker_id, DEAD, 0.0, 0, self.clock())
                self._workers[worker_id] = e
            elif e.state == DEAD:
                return False
            else:
                if e.state == DRAINING:
                    trip_breaker = False
                e.state = DEAD
        self._event("death")
        self._set_alive(worker_id, 0)
        if trip_breaker:
            from trino_tpu.runtime.retry import BREAKERS

            BREAKERS.get(worker_id).trip()
        return True

    def heartbeat(self, worker_id: str) -> None:
        """A successful probe/announce.  DEAD stays DEAD (sticky — see the
        class doc); live workers reset their miss count."""
        with self._lock:
            e = self._workers.get(worker_id)
            if e is None or e.state == DEAD:
                return
            e.last_heartbeat = self.clock()
            e.misses = 0

    def forget(self, worker_id: str) -> None:
        """Remove a worker from the registry entirely (a mesh SHRINK drops
        it by intent — a stale entry must not time out later and fail
        liveness checks that no longer concern it).  Unlike death this is
        not a failure event: no breaker trip, no death metric — only the
        liveness gauge drops."""
        with self._lock:
            if self._workers.pop(worker_id, None) is None:
                return
        self._set_alive(worker_id, 0)

    def miss(self, worker_id: str) -> int:
        """A failed probe; returns the consecutive-miss count."""
        with self._lock:
            e = self._workers.get(worker_id)
            if e is None or e.state == DEAD:
                return 0
            e.misses += 1
            return e.misses

    # -- views ----------------------------------------------------------------

    def get(self, worker_id: str) -> Optional[WorkerEntry]:
        with self._lock:
            return self._workers.get(worker_id)

    def state(self, worker_id: str) -> Optional[str]:
        e = self.get(worker_id)
        return None if e is None else e.state

    def active_workers(self) -> list:
        """Workers the next query's mesh may schedule on (ACTIVE only:
        DRAINING workers refuse new tasks, DEAD ones are gone).  Insertion
        order — stable task placement across queries."""
        with self._lock:
            return [w for w, e in self._workers.items() if e.state == ACTIVE]

    def probe_targets(self) -> list:
        """Workers the detector should ping (everything not DEAD)."""
        with self._lock:
            return [w for w, e in self._workers.items() if e.state != DEAD]

    def entries(self) -> list:
        """Point-in-time (worker_id, state, last_heartbeat) triples.  The
        list is built UNDER the lock, so callers iterate a stable snapshot
        — the fte detector's old dict.copy() refresh-race fix, subsumed by
        the registry lock (a concurrent heartbeat/register can never
        resize the dict mid-iteration here)."""
        with self._lock:
            return [
                (w, e.state, e.last_heartbeat)
                for w, e in self._workers.items()
            ]

    def snapshot(self) -> list:
        """system.runtime.nodes feed: (worker id, state, seconds since the
        last heartbeat, breaker state) per worker."""
        from trino_tpu.runtime.retry import BREAKER_CLOSED, BREAKERS

        breakers = BREAKERS.states()
        now = self.clock()
        with self._lock:
            return [
                (
                    w,
                    e.state,
                    (
                        round(now - e.last_heartbeat, 3)
                        if e.last_heartbeat
                        else None
                    ),
                    breakers.get(w, BREAKER_CLOSED),
                )
                for w, e in self._workers.items()
            ]

    # -- telemetry ------------------------------------------------------------

    @staticmethod
    def _event(kind: str) -> None:
        from trino_tpu.telemetry.metrics import membership_events_counter

        membership_events_counter().labels(kind).inc()

    @staticmethod
    def _set_alive(worker_id: str, v: int) -> None:
        from trino_tpu.telemetry.metrics import worker_alive_gauge

        worker_alive_gauge().labels(worker_id).set(v)


# -- heartbeat failure detection -----------------------------------------------


def http_probe(worker_url: str, timeout_s: float = 5.0) -> bool:
    """Default prober: does GET /v1/info answer?  DELIBERATELY laxer than
    the scheduler's `_StageScheduler._probe` (which only counts
    REFUSED/RESET, because it acts on one probe): the detector may count a
    timeout as a miss too, since declaring death takes miss-threshold
    CONSECUTIVE misses — an answering-but-slow worker misses once, then
    answers."""
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"{worker_url}/v1/info", timeout=timeout_s
        ) as r:
            r.read()
        return True
    except Exception:
        return False


class HeartbeatDetector:
    """Coordinator-side failure detector over a ClusterMembership
    (HeartbeatFailureDetector role, with the PR 5 breaker registry as the
    consumer).  `tick()` runs one probe round — deterministic for tests;
    `start()` runs rounds on a background thread at the configured
    interval (injectable sleep).

    Per round, per non-DEAD worker: a successful probe heartbeats the
    membership (never the breaker — an info answer is process liveness,
    not task-tier health); a failure counts a consecutive miss and votes
    failure on the breaker; `miss-threshold` consecutive misses declare
    the worker DEAD (membership trips the breaker OPEN).
    State is only evaluated at round boundaries, and DEAD is sticky, so a
    flapping worker cannot oscillate inside one probe window."""

    def __init__(
        self,
        membership: ClusterMembership,
        prober: Optional[Callable[[str], bool]] = None,
        config=None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        from trino_tpu.config import get_config

        self.membership = membership
        self._get_config = (lambda: config) if config is not None else (
            lambda: get_config().heartbeat
        )
        self.prober = prober or (
            lambda w: http_probe(w, self._get_config().probe_timeout_s)
        )
        self._sleep = sleep
        #: guards the start()/stop() check-then-act on _stop/_thread — a
        #: double start() racing itself must never leak a second probe loop
        self._loop_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: probe rounds completed (test/telemetry evidence)
        self.rounds = 0

    # ui.py compatibility: the coordinator dashboard asks the detector for
    # the live worker list
    def active_workers(self) -> list:
        return self.membership.active_workers()

    def tick(self) -> list:
        """One probe round; returns the workers declared DEAD this round.

        Probe FAILURES vote on the worker's breaker (real negative
        evidence); probe SUCCESSES only heartbeat the membership — a
        /v1/info answer proves process liveness, not task-tier health, so
        it must never close an OPEN breaker and short-circuit the cooldown
        real request failures earned."""
        from trino_tpu.runtime.retry import BREAKERS

        cfg = self._get_config()
        died = []
        for w in self.membership.probe_targets():
            ok = False
            try:
                ok = bool(self.prober(w))
            except Exception:
                ok = False
            if ok:
                self.membership.heartbeat(w)
            else:
                misses = self.membership.miss(w)
                if self.membership.state(w) != DRAINING:
                    # a DRAINING worker going silent is its drain
                    # completing by choice — no breaker vote (the trip
                    # would outrun mark_dead's own retirement carve-out)
                    BREAKERS.get(w).record_failure()
                if misses >= cfg.miss_threshold:
                    if self.membership.mark_dead(w):
                        died.append(w)
        self.rounds += 1
        return died

    def start(self, interval_s: Optional[float] = None) -> "HeartbeatDetector":
        """Background probe loop (daemon thread).  Idempotent AND atomic:
        two concurrent start() calls (e.g. two embedded servers adopting
        one runner) race on the _thread check — the loop lock makes the
        check-then-spawn a single step, so exactly one loop ever runs."""
        with self._loop_lock:
            if self._thread is not None:
                return self
            # each loop owns ITS stop event: a stopped loop's event stays
            # set forever, so a stop()/start() cycle can never leak a
            # second live loop racing the new one (the old thread may still
            # be inside its sleep when the new one starts)
            stop = threading.Event()
            self._stop = stop

            def loop():
                while not stop.is_set():
                    self.tick()
                    self._sleep(
                        interval_s
                        if interval_s is not None
                        else self._get_config().interval_s
                    )

            self._thread = threading.Thread(
                target=loop, daemon=True, name="heartbeat-detector"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._loop_lock:
            self._stop.set()
            self._thread = None


class HeartbeatFailureDetector:
    """Timeout-based liveness facade over a ``ClusterMembership`` — THE
    heartbeat failure detector (reference:
    failuredetector/HeartbeatFailureDetector.java:78, ping():350).

    This unifies the duplicate detector ``runtime/fte.py`` used to carry:
    the in-process mesh runner's timeout-based API (register / heartbeat /
    failed_workers / active_workers) is preserved, but the state now lives
    in the membership registry, so the mesh runner inherits sticky death,
    breaker integration (``mark_dead`` trips the worker's breaker OPEN),
    and the lock-guarded snapshot iteration that subsumed the old
    ``dict.copy()`` refresh-race fix (see ``ClusterMembership.entries``).

    Semantics preserved from the old detector:

      * a worker silent past ``timeout_s`` fails liveness checks,
      * a fresh ``heartbeat`` from a failed worker RECOVERS it — mapped to
        ``register`` (a worker-originated announce is the explicit rejoin
        intent sticky death requires; a mere probe success still cannot
        resurrect a DEAD worker, because probes route through
        ``ClusterMembership.heartbeat`` which keeps DEAD sticky),
      * ``unregister`` forgets a worker entirely (mesh shrink by intent).
    """

    def __init__(
        self,
        timeout_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        membership: Optional[ClusterMembership] = None,
    ):
        self.timeout_s = timeout_s
        # an explicitly provided membership keeps ITS clock; otherwise the
        # facade's clock argument seeds the registry it creates
        self.membership = (
            membership if membership is not None
            else ClusterMembership(clock=clock)
        )

    # the detector and its registry share ONE time source (the old
    # detector's semantics): overriding `detector.clock` must move the
    # heartbeat timestamps too, or a test-shifted clock would mark every
    # worker stale the instant it heartbeats
    @property
    def clock(self) -> Callable[[], float]:
        return self.membership.clock

    @clock.setter
    def clock(self, fn: Callable[[], float]) -> None:
        self.membership.clock = fn

    def register(self, worker: str) -> None:
        self.membership.register(worker)

    def unregister(self, worker: str) -> None:
        self.membership.forget(worker)

    def heartbeat(self, worker: str) -> None:
        # a worker-originated announce: refreshes a live worker, rejoins a
        # DEAD one (registration is the explicit resurrection intent)
        self.membership.register(worker)

    def refresh(self) -> None:
        """Mark every worker silent past the timeout DEAD (sticky; trips
        its breaker).  Iterates a lock-built snapshot — see entries()."""
        now = self.clock()
        for w, state, last in self.membership.entries():
            if state != DEAD and now - last > self.timeout_s:
                self.membership.mark_dead(w)

    def failed_workers(self) -> set:
        self.refresh()
        return {
            w for w, state, _ in self.membership.entries() if state == DEAD
        }

    def active_workers(self) -> list:
        self.refresh()
        return sorted(
            w for w, state, _ in self.membership.entries() if state == ACTIVE
        )

    def is_alive(self, worker: str) -> bool:
        self.refresh()
        return self.membership.state(worker) in (ACTIVE, DRAINING)


# -- mesh-signature cache invalidation -----------------------------------------


def invalidate_mesh_scans(mesh_sig=None) -> int:
    """Drop device-resident stacked-scan cache entries for a mesh signature
    (all mesh signatures when None).  A query re-planned at a different W
    shards scans differently — entries keyed by the OLD signature are dead
    weight holding HBM, and must never alias the new mesh's keys.  Returns
    the number of entries dropped."""
    from trino_tpu.runtime.buffer_pool import POOL

    def stale(key) -> bool:
        if not (isinstance(key, tuple) and key and key[0] == "mesh_scan"):
            return False
        return mesh_sig is None or (len(key) > 1 and key[1] == mesh_sig)

    return POOL.invalidate_device(stale)
