"""In-process query runner: SQL text -> materialized results.

Reference role: testing/LocalQueryRunner.java:260 — the full
parse -> analyze -> plan -> execute pipeline in one process, no RPC; results
captured the way PageConsumerOperator captures pages.  This is both the test
harness entry point and the kernel of the single-node engine.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from trino_tpu.connectors.api import CatalogManager, default_catalogs
from trino_tpu.planner.logical_planner import LogicalPlanner, Session
from trino_tpu.planner.plan import OutputNode, plan_text
from trino_tpu.runtime.local_planner import LocalExecutionPlanner
from trino_tpu.sql import ast
from trino_tpu.sql.parser import parse_statement


@dataclass
class MaterializedResult:
    """Reference role: testing/MaterializedResult.java."""

    column_names: list
    rows: list  # list of tuples of python values
    types: list

    @property
    def row_count(self) -> int:
        return len(self.rows)

    def only_value(self):
        assert len(self.rows) == 1 and len(self.rows[0]) == 1, self.rows
        return self.rows[0][0]

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.rows, columns=self.column_names)


class LocalQueryRunner:
    def __init__(
        self,
        catalogs: Optional[CatalogManager] = None,
        catalog: str = "tpch",
        schema: str = "tiny",
        target_splits: int = 4,
    ):
        from trino_tpu.runtime.events import EventListenerManager
        from trino_tpu.runtime.session import SessionProperties
        from trino_tpu.runtime.transactions import TransactionManager

        self.catalogs = catalogs or default_catalogs()
        self.session = Session(catalog, schema)
        self.properties = SessionProperties()
        self.properties.set("target_splits", target_splits)
        self.events = EventListenerManager()
        self.transactions = TransactionManager(self.catalogs)
        # security (server/security/ + spi/security/SystemAccessControl):
        # identity set per statement by the coordinator/dbapi layer
        from trino_tpu.server.security import AllowAllAccessControl, GrantManager

        self.access_control = AllowAllAccessControl()
        #: SQL-standard grants/roles store consulted by GRANT/REVOKE DDL and
        #: by SqlStandardAccessControl when installed (reference:
        #: MetadataManager.grantTablePrivileges)
        self.grants = GrantManager()
        self.user = "user"
        self._query_ids = __import__("itertools").count(1)
        # query lifecycle (runtime/lifecycle; reference: QueryTracker +
        # QueryStateMachine): per-query deadline + cooperative cancellation;
        # DELETE /v1/query/{id} and the low-memory killer resolve through it
        from trino_tpu.runtime.lifecycle import QueryTracker

        self.query_tracker = QueryTracker()
        #: one-shot hook: called with the next statement's QueryContext as
        #: soon as it exists (the coordinator attaches its cancel surface
        #: race-free — the engine lock serializes executions around it)
        self._query_context_cb = None
        # system.runtime observability (connector/system/ role): query
        # history + nodes + session properties queryable via SQL
        from trino_tpu.connectors.system import QueryHistory, SystemConnector

        self.query_history = QueryHistory()
        self.events.add(self.query_history)
        #: (catalog, schema, name) -> view definition Query AST (reference:
        #: MetadataManager view storage + sql/tree/CreateView.java)
        self.views: dict[tuple, object] = {}
        #: prepared-statement name -> statement TEXT with `?` placeholders
        #: (reference: server/protocol prepared-statement headers)
        self.prepared: dict[str, str] = {}
        if "system" not in self.catalogs.names():
            sysconn = SystemConnector(self)
            self.catalogs.register("system", sysconn)
        else:
            sysconn = self.catalogs.get("system")
        if getattr(sysconn, "runner", None) is None:
            sysconn.runner = self
        # telemetry: per-query span tracer (telemetry/spans; NULL when the
        # query_trace session property is off) + recent trace history
        # feeding system.runtime.spans and the coordinator trace endpoint.
        # The tracer / last_trace / last_mesh_profile / peak-memory
        # surfaces are PROPERTIES resolved through the lifecycle
        # contextvar: inside a statement they read that statement's
        # handles, so concurrent engine lanes (and legacy direct
        # execute() callers on one shared runner) can never observe each
        # other's EXPLAIN ANALYZE profile or trace; the plain attributes
        # below are the most-recently-finished fallbacks bench/tests read
        # after execute() returns.
        from collections import deque

        from trino_tpu.telemetry import NULL_TRACER

        self._tracer = NULL_TRACER
        #: Chrome-trace/Perfetto JSON of the most recent traced query
        self.last_trace = None
        #: (query_id, flattened spans) ring buffer (system.runtime.spans)
        self.traces = deque(maxlen=64)
        #: peak device-memory reservation of the last local execution
        self._last_peak_memory = 0
        #: persistent per-query profile archive (telemetry/profile_store):
        #: None = archiving off (zero cost).  Attached at the load points
        #: that know the config — runner_from_etc and
        #: CoordinatorServer.start (attach_profile_store) — or explicitly;
        #: NOT here, so clone_for_dispatch lane construction never builds
        #: a throwaway store it immediately replaces with the parent's.
        self.profile_store = None

    def clone_for_dispatch(self) -> "Optional[LocalQueryRunner]":
        """An engine-lane clone for the concurrent dispatcher
        (runtime/dispatcher.QueryDispatcher): shares everything whose
        identity matters across lanes — catalogs (and through them the
        system connector bound to THIS runner), the query tracker and id
        counter (DELETE-cancel and unique ids resolve process-wide), the
        event pipeline + query history (one system.runtime.queries), the
        session-property store (SET SESSION keeps its engine-wide
        semantics), views/prepared/grants/access control, and the span
        ring — while per-statement state (tracer, last_trace, peak memory,
        mesh profile, user) stays lane-private so host-side planning and
        result serialization overlap safely.  Subclasses (distributed /
        multi-host runners) return None: their worker management cannot be
        cloned, so the dispatcher degrades to one lane."""
        if type(self) is not LocalQueryRunner:
            return None
        lane = LocalQueryRunner(
            self.catalogs, self.session.catalog, self.session.schema
        )
        lane.session = self.session
        lane.properties = self.properties
        # ONE transaction state across lanes: the HTTP protocol has no
        # session affinity, so a BEGIN landing on lane 3 and its COMMIT on
        # lane 2 must see the same TransactionManager (exactly the single
        # shared runner's pre-dispatcher semantics)
        lane.transactions = self.transactions
        lane.events = self.events
        lane.query_history = self.query_history
        lane.query_tracker = self.query_tracker
        lane._query_ids = self._query_ids
        lane.views = self.views
        lane.prepared = self.prepared
        lane.grants = self.grants
        lane.access_control = self.access_control
        lane.traces = self.traces
        lane.profile_store = self.profile_store
        return lane

    # -- per-statement telemetry handles (lane safety) -------------------------
    #
    # Resolution rule shared by all four surfaces: INSIDE a statement the
    # lifecycle contextvar names that statement's own handle (concurrent
    # lanes and legacy multi-threaded direct execute() callers each see
    # their own); OUTSIDE one, the most-recently-finished statement's value
    # (what bench / verify.device_residency read after execute returns).
    # Setters write the statement handle AND the shared fallback — last
    # writer wins on the fallback, which is exactly the pre-lane semantics.

    #: class-level defaults so the properties read cleanly on runners that
    #: never executed (LocalQueryRunner has no mesh profile at all)
    _last_mesh_profile = None

    @property
    def _tracer(self):
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None and ctx.tracer is not None:
            return ctx.tracer
        return self._tracer_default

    @_tracer.setter
    def _tracer(self, tracer) -> None:
        self._tracer_default = tracer

    @property
    def last_mesh_profile(self):
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None and ctx.mesh_profile is not None:
            return ctx.mesh_profile
        return self._last_mesh_profile

    @last_mesh_profile.setter
    def last_mesh_profile(self, profile) -> None:
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None:
            ctx.mesh_profile = profile
        self._last_mesh_profile = profile

    @property
    def last_trace(self):
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None and ctx.trace_json is not None:
            return ctx.trace_json
        return self._last_trace

    @last_trace.setter
    def last_trace(self, trace) -> None:
        self._last_trace = trace

    @property
    def _last_peak_memory(self) -> int:
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None:
            return ctx.peak_memory
        return self._last_peak

    @_last_peak_memory.setter
    def _last_peak_memory(self, peak: int) -> None:
        from trino_tpu.runtime.lifecycle import current_query

        ctx = current_query()
        if ctx is not None:
            ctx.peak_memory = peak
        self._last_peak = peak

    @property
    def in_transaction(self) -> bool:
        return self.transactions.active

    @property
    def target_splits(self) -> int:
        return self.properties.get("target_splits")

    # -- planning -------------------------------------------------------------

    def create_plan(self, sql: str) -> OutputNode:
        stmt = parse_statement(sql)
        if not isinstance(stmt, ast.SelectStatement):
            raise NotImplementedError(f"statement: {type(stmt).__name__}")
        return self.plan_query(stmt.query)

    def plan_query(self, query: ast.Query) -> OutputNode:
        from trino_tpu.runtime.lifecycle import check_current_planning

        tr = self._tracer
        check_current_planning()  # query_max_planning_time / cancel token
        with tr.span("analyze"):
            query = self._expand_recursive_ctes(query)
            plan = LogicalPlanner(
                self.catalogs, self.session, views=self.views
            ).plan(query)
        check_current_planning()
        with tr.span("optimize"):
            out = self.optimize(plan)
        check_current_planning()
        return out

    def optimize(self, plan: OutputNode) -> OutputNode:
        from trino_tpu.planner.optimizer import optimize

        return optimize(
            plan,
            catalogs=self.catalogs,
            verify=self.properties.get("verify_plan"),
        )

    def explain(self, sql: str) -> str:
        return plan_text(self.create_plan(sql))

    # -- execution ------------------------------------------------------------

    def execute(self, sql: str) -> MaterializedResult:
        """Execute any supported statement (reference role: the statement
        dispatch of LocalQueryRunner.executeInternal + DDL *Task executors
        under execution/), with query events, telemetry (root span +
        registry metrics + QueryStatistics payload), and retry-policy
        handling."""
        import time as _time

        from trino_tpu.runtime.events import (
            QueryCompletedEvent,
            QueryCreatedEvent,
            classify_error,
        )
        from trino_tpu.runtime.retry import execute_with_retry
        from trino_tpu.runtime.session import CURRENT_USER
        from trino_tpu.telemetry import NULL_TRACER, SpanTracer
        from trino_tpu.telemetry.metrics import (
            queries_counter,
            query_wall_histogram,
        )

        self.access_control.check_can_execute_query(self.user)
        CURRENT_USER.set(self.user)
        stmt = parse_statement(sql)
        m = getattr(self, "_exec_" + type(stmt).__name__, None)
        if m is None:
            raise NotImplementedError(f"statement: {type(stmt).__name__}")
        from trino_tpu.runtime import lifecycle

        qid = f"query_{next(self._query_ids)}"
        self._current_qid = qid  # correlates events with executor/spool ids
        # lifecycle context: deadline from the query_max_run_time /
        # query_max_planning_time session properties, cancellation token
        # consulted at fragment/batch/launch boundaries, published through
        # the contextvar so deep call sites need no handle
        ctx = self.query_tracker.create(qid, self.properties)
        cb, self._query_context_cb = self._query_context_cb, None
        if cb is not None:
            cb(ctx)
        token = lifecycle.set_current(ctx)
        tracer = (
            SpanTracer(query_id=qid)
            if self.properties.get("query_trace")
            else NULL_TRACER
        )
        prev_tracer = self._tracer  # nested execute (EXECUTE stmt) restores
        self._tracer = tracer
        # the statement's own handle (NULL_TRACER included): concurrent
        # lanes resolve THEIR tracer through the lifecycle contextvar, so
        # an untraced statement can never record into a traced neighbor's
        # tree through the shared fallback attribute (lane safety)
        ctx.tracer = tracer
        # plan-decision ledger: attached per-statement like the tracer, so
        # concurrent lanes record into disjoint ledgers (lane safety)
        from trino_tpu.telemetry.decisions import ensure_ledger

        ensure_ledger(ctx)
        t0 = _time.time()
        self.events.query_created(QueryCreatedEvent(qid, sql, t0))
        try:
            ctx.begin()
            with tracer.span("query", query_id=qid, sql=sql[:200]):
                self._record_queue_span(tracer)
                # fault_tolerant_execution implies per-task retry: the
                # spool/dedup machinery only engages under the TASK policy,
                # so the session flag promotes NONE -> TASK (an explicit
                # QUERY policy wins — the user asked for whole-query rerun)
                policy = self.properties.get("retry_policy")
                if (
                    policy == "NONE"
                    and self.properties.get("fault_tolerant_execution")
                ):
                    policy = "TASK"
                result = execute_with_retry(lambda: m(stmt), policy)
            ctx.finish()
        except BaseException as e:
            end = _time.time()
            state = ctx.fail(e)  # CANCELED for user cancels, else FAILED
            etype = classify_error(e)
            queries_counter().labels(state, etype).inc()
            query_wall_histogram().observe(end - t0)
            self._finish_trace(qid, tracer, prev_tracer, ctx)
            self._finalize_decisions(ctx)
            self._archive_profile(
                ctx, sql, state, end - t0,
                error_code=getattr(e, "error_code", None),
            )
            self.events.query_completed(
                QueryCompletedEvent(
                    qid, sql, state, t0, end, error=str(e),
                    error_type=etype,
                    error_code=getattr(e, "error_code", None),
                    statistics=self._query_statistics(
                        end - t0, 0, tracer, ctx
                    ),
                )
            )
            raise
        finally:
            lifecycle.reset_current(token)
            ctx.release_spills()  # aborted waves must not leak npz files
            ctx.release_memory()  # shared-pool reservations end with us
            self.query_tracker.remove(ctx)
        end = _time.time()
        queries_counter().labels("FINISHED", "").inc()
        query_wall_histogram().observe(end - t0)
        self._finish_trace(qid, tracer, prev_tracer, ctx)
        self._finalize_decisions(ctx)
        self._archive_profile(
            ctx, sql, "FINISHED", end - t0, rows=result.row_count
        )
        self.events.query_completed(
            QueryCompletedEvent(
                qid, sql, "FINISHED", t0, end, rows=result.row_count,
                statistics=self._query_statistics(
                    end - t0, result.row_count, tracer, ctx
                ),
            )
        )
        return result

    def _record_queue_span(self, tracer) -> None:
        """When this statement came through the dispatcher's admission
        queue, record its wait as a `queued` span under the query root so
        the trace shows admission latency next to execution (reference:
        the DispatchManager queued-state span)."""
        if not tracer.enabled:
            return
        from trino_tpu.runtime.lifecycle import current_admission
        from trino_tpu.telemetry.spans import now as _now

        adm = current_admission()
        if adm is None:
            return
        group, queued_s = adm
        end = _now()
        tracer.record(
            "queued", end - max(0.0, queued_s), end,
            {"group": group, "queued_s": round(queued_s, 6)},
        )

    def _finish_trace(self, qid: str, tracer, prev_tracer, ctx=None) -> None:
        """Export the finished query's spans (Chrome JSON + the flattened
        history row feeding system.runtime.spans).  Stores the export on
        the statement's own lifecycle context too, so the coordinator's
        trace endpoint reads THIS query's trace even while other lanes
        keep finishing (lane safety)."""
        self._tracer = prev_tracer
        if not tracer.enabled:
            return
        if ctx is not None and ctx.gate_wait_s > 0 and tracer.root is not None:
            # device-gate contention next to the spans it delayed
            tracer.root.attrs["gate_wait_s"] = round(ctx.gate_wait_s, 6)
        trace = tracer.to_chrome_trace()
        if ctx is not None:
            ctx.trace_json = trace
        self.last_trace = trace
        self.traces.append((qid, tracer.flat_spans()))

    def _finalize_decisions(self, ctx) -> None:
        """Join the statement's plan-decision ledger with its measured
        outcomes and stamp hindsight verdicts (telemetry/decisions).
        Runs before the profile artifact is assembled so the ledger lands
        in it.  Host-side arithmetic on integers the profile already
        holds; must never break a query."""
        ledger = getattr(ctx, "decisions", None)
        if ledger is None:
            return
        try:
            wm = getattr(self, "wm", None)
            n = wm.n if wm is not None else 1
            prof = ctx.mesh_profile
            phases = (
                {fid: st.wall_s for fid, st in prof.fragments.items()}
                if prof is not None
                else None
            )
            ledger.finalize(
                n_workers=n,
                regret_ratio=float(
                    self.properties.get("decision_regret_ratio")
                ),
                min_bytes=int(
                    self.properties.get("decision_regret_min_bytes")
                ),
                fragment_phases=phases,
            )
        except Exception:
            import logging

            logging.getLogger("trino_tpu.decisions").warning(
                "failed to finalize decision ledger for %s", ctx.query_id,
                exc_info=True,
            )

    def _archive_profile(self, ctx, sql: str, state: str, wall_s: float,
                         rows: int = 0, error_code=None) -> None:
        """Assemble + archive this statement's profile artifact
        (telemetry/profile_store) when a store is attached.  Assembly is
        host-side dict building; the SPI write happens on the store's
        background writer — off the statement hot path, after FINISHING.
        Archiving must never break a query."""
        store = getattr(self, "profile_store", None)
        if store is None:
            return
        try:
            from trino_tpu.telemetry.profile_store import artifact_from_runner

            ctx.profile_ref = store.archive(
                artifact_from_runner(
                    self, ctx, sql, state, wall_s, rows=rows,
                    error_code=error_code,
                )
            )
        except Exception:
            import logging

            logging.getLogger("trino_tpu.profile_store").warning(
                "failed to assemble profile artifact for %s", ctx.query_id,
                exc_info=True,
            )

    def compile_manifest(self) -> list:
        """The deduplicated (step, bucket, mesh) compile-key set this
        process's workload has needed, with per-key compile seconds — the
        compile observatory's prewarm manifest (the enumeration input for
        AOT prewarm / ROADMAP item 3; dumped by tools/prewarm_manifest.py).
        A workload whose warm replays add zero entries has a closed key
        set: prewarming exactly this manifest makes its cold start warm."""
        from trino_tpu.telemetry.compile_events import OBSERVATORY

        return OBSERVATORY.manifest()

    def _query_statistics(self, wall_s: float, rows: int, tracer, ctx):
        """Build the QueryStatistics event payload from the statement's
        OWN lifecycle handles (mesh profile when distributed, span count,
        peak memory, device-gate wait, admission info) — per-statement by
        construction, so concurrent lanes can't cross-attribute."""
        from trino_tpu.runtime.events import QueryStatistics
        from trino_tpu.runtime.lifecycle import current_admission

        stats = QueryStatistics(wall_s=round(wall_s, 6), rows=rows)
        prof = ctx.mesh_profile
        if prof is not None:
            stats.phase_totals_s = prof.phase_totals()
            stats.counters = dict(prof.counters)
            stats.trace_cache = {
                "hits": prof.trace_hits,
                "misses": prof.trace_misses,
                "retraces": prof.retraces,
            }
        stats.peak_memory_bytes = ctx.peak_memory
        stats.gate_wait_s = round(ctx.gate_wait_s, 6)
        adm = current_admission()
        if adm is not None:
            stats.group, stats.queued_s = adm[0], round(adm[1], 6)
        ref = ctx.profile_ref
        if ref is not None:
            stats.profile_key = ref["key"]
        if tracer.enabled:
            stats.spans = len(tracer.flat_spans())
        return stats

    def _check_table_access(self, plan) -> None:
        """check_can_select for every scanned table (the reference checks in
        the analyzer; checking the optimized plan also covers views/CTEs)."""
        from trino_tpu.planner.plan import TableScanNode

        def walk(node):
            if isinstance(node, TableScanNode):
                h = node.handle
                self.access_control.check_can_select(
                    self.user, h.catalog, h.schema, h.table
                )
            for c in node.children:
                walk(c)

        walk(plan)

    #: WITH RECURSIVE iteration cap (reference: the max_recursion_depth
    #: session property guarding RecursiveCte expansion)
    MAX_RECURSION_DEPTH = 100

    def _expand_recursive_ctes(self, query: ast.Query) -> ast.Query:
        """WITH RECURSIVE t AS (anchor UNION [ALL] step) — iterate to a
        fixpoint and replace the CTE with its materialized rows (reference:
        sql/planner's recursive CTE expansion, which the reference also
        bounds by max-recursion-depth; here each step plans the recursive
        term against a VALUES relation of the previous delta)."""
        if not query.recursive:
            return query

        def references(node, name) -> bool:
            if isinstance(node, ast.TableRef) and node.name == (name,):
                return True

            def walk_tuple(t) -> bool:
                for item in t:
                    if isinstance(item, ast.Node) and references(item, name):
                        return True
                    if isinstance(item, tuple) and walk_tuple(item):
                        return True
                return False

            for f in getattr(node, "__dataclass_fields__", {}):
                v = getattr(node, f)
                if isinstance(v, ast.Node) and references(v, name):
                    return True
                if isinstance(v, tuple) and walk_tuple(v):
                    return True
            return False

        new_ctes = []
        for w in query.ctes:
            if not references(w.query, w.name):
                new_ctes.append(w)
                continue
            if w.query.order_by or w.query.limit is not None or w.query.offset:
                raise NotImplementedError(
                    "ORDER BY/LIMIT inside a recursive CTE definition"
                )
            body = w.query.body
            if not (isinstance(body, ast.SetOp) and body.op == "union"):
                raise NotImplementedError(
                    "recursive CTE must be anchor UNION [ALL] recursive-term"
                )
            anchor, step = body.left, body.right
            if references(anchor, w.name):
                raise NotImplementedError(
                    "recursive CTE anchor must not reference the CTE"
                )
            # the CTE definition's own nested WITH entries stay in scope for
            # both the anchor and every recursive step
            prior_ctes = tuple(new_ctes) + tuple(w.query.ctes)
            res = self._run_query(ast.Query(anchor, ctes=prior_ctes))
            names = list(w.column_names) or list(res.column_names)
            distinct = not body.all
            total: list = []
            seen: set = set()
            for r in res.rows:
                t = tuple(r)
                if distinct:
                    if t in seen:
                        continue
                    seen.add(t)
                total.append(r)
            cur_types = list(res.types)
            work = list(total) if distinct else list(res.rows)
            for _ in range(self.MAX_RECURSION_DEPTH):
                if not work:
                    break
                bound = ast.Query(
                    step,
                    ctes=prior_ctes
                    + (
                        ast.WithQuery(
                            w.name,
                            ast.Query(_values_relation(work, cur_types)),
                            tuple(names),
                        ),
                    ),
                )
                nxt = self._run_query(bound)
                # UNION coercion: widen the carried types so step values
                # are never cast back down to the anchor's narrower type
                from trino_tpu import types as T

                cur_types = [
                    T.common_super_type(a, b)
                    for a, b in zip(cur_types, nxt.types)
                ]
                rows = []
                for r in nxt.rows:
                    t = tuple(r)
                    if distinct:
                        if t in seen:
                            continue
                        seen.add(t)
                    rows.append(r)
                if not rows:
                    break
                total.extend(rows)
                work = rows
            else:
                raise RuntimeError(
                    f"recursive CTE {w.name} exceeded "
                    f"{self.MAX_RECURSION_DEPTH} iterations"
                )
            new_ctes.append(
                ast.WithQuery(
                    w.name,
                    ast.Query(_values_relation(total, cur_types, names)),
                    tuple(names),
                )
            )
        return ast.Query(
            query.body, query.order_by, query.limit, query.offset,
            tuple(new_ctes), False,
        )

    def _execute_plan(self, plan, stats=None) -> MaterializedResult:
        """Run an already-planned query in THIS process (also the multihost
        runner's path for coordinator-resident system-catalog queries).

        Concurrent serving: each device step — pipeline construction
        (which drains blocking builds) and every batch pull — runs under
        the process-wide `device_slice()` gate, so concurrent engine lanes
        interleave device work at fragment/batch boundaries (feed/step/
        drain, no preemption) while row serialization below stays outside
        the gate and overlaps other lanes' device time."""
        from trino_tpu.runtime.dispatcher import device_slice
        from trino_tpu.runtime.lifecycle import check_current

        with self._tracer.span("execute"):
            with device_slice():
                lp = LocalExecutionPlanner(
                    self.catalogs,
                    target_splits=self.target_splits,
                    stats=stats,
                    properties=self.properties,
                )
                physical = lp.plan(plan)
            rows = []
            it = iter(physical.stream)
            done = object()
            while True:
                with device_slice():
                    batch = next(it, done)
                if batch is done:
                    break
                check_current()  # cancel/deadline between result batches
                rows.extend(tuple(r) for r in batch.to_pylist())
            self._last_peak_memory = lp.memory.peak
        return MaterializedResult(
            list(plan.column_names), rows, [s.type for s in plan.symbols]
        )

    def _run_query(self, query: ast.Query, stats=None) -> MaterializedResult:
        plan = self.plan_query(query)
        self._check_table_access(plan)

        def run() -> MaterializedResult:
            return self._execute_plan(plan, stats=stats)

        profile_dir = self.properties.get("profile_dir")
        if profile_dir:
            # device-kernel attribution (reference role: OperatorStats'
            # per-operator CPU/wall split; here the XLA profiler records the
            # actual device kernels — open the trace with tensorboard or
            # xprof)
            import jax

            with jax.profiler.trace(profile_dir):
                return run()
        return run()

    def _exec_SelectStatement(self, stmt: ast.SelectStatement) -> MaterializedResult:
        return self._run_query(stmt.query)

    # -- EXPLAIN --------------------------------------------------------------

    def _exec_ExplainStatement(self, stmt: ast.ExplainStatement) -> MaterializedResult:
        from trino_tpu import types as T

        inner = stmt.statement
        if not isinstance(inner, ast.SelectStatement):
            raise NotImplementedError("EXPLAIN supports queries only")
        if stmt.analyze:
            from trino_tpu.runtime.query_stats import StatsCollector

            collector = StatsCollector()
            self._run_query(inner.query, stats=collector)
            text = collector.render()
            if stmt.verbose:
                # VERBOSE: append the span tree + the Perfetto-loadable
                # Chrome-trace JSON (one line, machine-extractable)
                import json as _json

                tr = self._tracer
                text += "\n" + tr.render_text()
                if tr.enabled:
                    text += "\nTrace JSON: " + _json.dumps(
                        tr.to_chrome_trace()
                    )
        elif stmt.explain_type == "distributed":
            # fragments + partitioning handles, even from a local runner
            # (reference: EXPLAIN (TYPE DISTRIBUTED) -> PlanFragmenter)
            from trino_tpu.planner.fragmenter import (
                add_exchanges,
                create_subplans,
                fragment_text,
            )

            plan = self.plan_query(inner.query)
            sub = create_subplans(
                add_exchanges(plan, self.catalogs, self.properties),
                properties=self.properties,
                catalogs=self.catalogs,
            )
            text = fragment_text(sub)
        else:
            text = plan_text(self.plan_query(inner.query))
            from trino_tpu.planner import optimizer as _opt

            if _opt.LAST_RULE_STATS:
                fires = ", ".join(
                    f"{k}={v}"
                    for k, v in sorted(_opt.LAST_RULE_STATS.items())
                )
                text += f"\nrule fires: {fires}"
        return MaterializedResult(
            ["Query Plan"], [(line,) for line in text.splitlines()], [T.VARCHAR]
        )

    # -- session statements ---------------------------------------------------

    def _exec_UseStatement(self, stmt: ast.UseStatement) -> MaterializedResult:
        if stmt.catalog:
            self.catalogs.get(stmt.catalog)  # validate
            self.session = Session(stmt.catalog, stmt.schema)
        else:
            self.session = Session(self.session.catalog, stmt.schema)
        return _ok("USE")

    def _exec_SetSession(self, stmt: ast.SetSession) -> MaterializedResult:
        from trino_tpu.planner.analyzer import ExprAnalyzer, Scope
        from trino_tpu.expr.ir import Literal

        e = ExprAnalyzer(Scope([])).analyze(stmt.value)
        if not isinstance(e, Literal):
            raise ValueError("SET SESSION value must be a literal")
        value = e.value
        if e.type.name.startswith("varchar"):
            value = str(value)
        self.properties.set(stmt.name, value)
        return _ok("SET SESSION")

    def _exec_TransactionStatement(self, stmt: ast.TransactionStatement) -> MaterializedResult:
        if stmt.action == "start":
            self.transactions.begin()
            return _ok("START TRANSACTION")
        if stmt.action == "commit":
            self.transactions.commit()
            return _ok("COMMIT")
        self.transactions.rollback()
        return _ok("ROLLBACK")

    # -- SHOW / DESCRIBE ------------------------------------------------------

    def _exec_ShowStatement(self, stmt: ast.ShowStatement) -> MaterializedResult:
        from trino_tpu import types as T

        if stmt.what == "catalogs":
            return MaterializedResult(
                ["Catalog"], [(n,) for n in sorted(self.catalogs.names())], [T.VARCHAR]
            )
        if stmt.what == "schemas":
            cat = stmt.target[0] if stmt.target else self.session.catalog
            conn = self.catalogs.get(cat)
            return MaterializedResult(
                ["Schema"],
                [(s,) for s in sorted(conn.metadata().list_schemas())],
                [T.VARCHAR],
            )
        if stmt.what == "tables":
            if len(stmt.target) == 2:
                cat, schema = stmt.target
            elif len(stmt.target) == 1:
                cat, schema = self.session.catalog, stmt.target[0]
            else:
                cat, schema = self.session.catalog, self.session.schema
            conn = self.catalogs.get(cat)
            return MaterializedResult(
                ["Table"],
                [(t,) for t in sorted(conn.metadata().list_tables(schema))],
                [T.VARCHAR],
            )
        if stmt.what == "columns":
            cat, schema, table = self._resolve_table(stmt.target)
            meta = self.catalogs.get(cat).metadata().table_metadata(schema, table)
            return MaterializedResult(
                ["Column", "Type"],
                [(c.name, c.type.name) for c in meta.columns],
                [T.VARCHAR, T.VARCHAR],
            )
        if stmt.what == "functions":
            from trino_tpu.planner.registry import global_registry
            from trino_tpu.expr.strings import like_to_regex

            rows = [
                (
                    m.name,
                    m.return_type,
                    ", ".join(m.argument_types),
                    m.kind,
                    m.deterministic,
                    m.description,
                )
                for m in global_registry().list()
            ]
            if stmt.target:
                rx = like_to_regex(stmt.target[0])
                rows = [r for r in rows if rx.match(r[0])]
            return MaterializedResult(
                [
                    "Function",
                    "Return Type",
                    "Argument Types",
                    "Function Type",
                    "Deterministic",
                    "Description",
                ],
                rows,
                [T.VARCHAR, T.VARCHAR, T.VARCHAR, T.VARCHAR, T.BOOLEAN, T.VARCHAR],
            )
        if stmt.what == "create_table":
            # reference: sql/rewrite/ShowQueriesRewrite's SHOW CREATE TABLE
            cat, schema, table = self._resolve_table(stmt.target)
            meta = self.catalogs.get(cat).metadata().table_metadata(schema, table)
            cols = ",\n".join(
                f"   {c.name} {c.type.name}" for c in meta.columns
            )
            ddl = f"CREATE TABLE {cat}.{schema}.{table} (\n{cols}\n)"
            return MaterializedResult(["Create Table"], [(ddl,)], [T.VARCHAR])
        if stmt.what == "roles":
            return MaterializedResult(
                ["Role"], [(r,) for r in self.grants.list_roles()], [T.VARCHAR]
            )
        if stmt.what == "grants":
            if stmt.target:
                cat, schema, table = self._resolve_table(stmt.target)
                rows = self.grants.grants_for(cat, schema, table)
            else:
                rows = self.grants.grants_for()
            return MaterializedResult(
                ["grantee", "privilege", "catalog", "schema", "table"],
                rows,
                [T.VARCHAR] * 5,
            )
        if stmt.what == "stats":
            # reference: sql/rewrite/ShowStatsRewrite.java — one row per
            # column plus a NULL-named summary row carrying row_count
            cat, schema, table = self._resolve_table(stmt.target)
            md = self.catalogs.get(cat).metadata()
            meta = md.table_metadata(schema, table)
            ts = md.table_statistics(schema, table)
            rows = []
            for c in meta.columns:
                cs = ts.columns.get(c.name)
                rows.append(
                    (
                        c.name,
                        None,
                        float(cs.distinct_count) if cs and cs.distinct_count else None,
                        float(cs.null_fraction) if cs else None,
                        None,
                        str(cs.low) if cs and cs.low is not None else None,
                        str(cs.high) if cs and cs.high is not None else None,
                    )
                )
            rows.append(
                (
                    None, None, None, None,
                    float(ts.row_count) if ts.row_count is not None else None,
                    None, None,
                )
            )
            return MaterializedResult(
                [
                    "column_name", "data_size", "distinct_values_count",
                    "nulls_fraction", "row_count", "low_value", "high_value",
                ],
                rows,
                [T.VARCHAR, T.DOUBLE, T.DOUBLE, T.DOUBLE, T.DOUBLE, T.VARCHAR, T.VARCHAR],
            )
        if stmt.what == "session":
            rows = [
                (name, str(value), meta.type.__name__, meta.description)
                for name, value, meta in sorted(self.properties.items())
            ]
            return MaterializedResult(
                ["Name", "Value", "Type", "Description"],
                rows,
                [T.VARCHAR, T.VARCHAR, T.VARCHAR, T.VARCHAR],
            )
        raise NotImplementedError(f"SHOW {stmt.what}")

    def _resolve_table(self, parts: tuple) -> tuple:
        if len(parts) == 3:
            return parts
        if len(parts) == 2:
            return (self.session.catalog, parts[0], parts[1])
        return (self.session.catalog, self.session.schema, parts[0])

    # -- DDL / DML (reference: execution/CreateTableTask, DropTableTask,
    # InsertStatement via TableWriterOperator -> ConnectorPageSink) ----------

    @staticmethod
    def _table_layout_from(properties: tuple, column_names) -> "object":
        """Extract a TableLayout from CREATE TABLE WITH (...) properties
        (reference: connector table properties -> bucketing handle)."""
        from trino_tpu.partitioning import TableLayout

        props = dict(properties or ())
        unknown = set(props) - {"bucketed_by", "bucket_count"}
        if unknown:
            raise ValueError(
                f"unknown table properties: {sorted(unknown)} "
                "(supported: bucketed_by, bucket_count)"
            )
        if not props:
            return None
        cols = props.get("bucketed_by")
        count = props.get("bucket_count")
        if not cols or not count:
            raise ValueError(
                "bucketed tables need BOTH bucketed_by and bucket_count"
            )
        cols = tuple(str(c) for c in (cols if isinstance(cols, tuple) else (cols,)))
        missing = [c for c in cols if c not in list(column_names)]
        if missing:
            raise ValueError(f"bucketed_by names unknown columns: {missing}")
        return TableLayout(cols, int(count))

    @staticmethod
    def _create_with_layout(conn, schema, table, cols, layout) -> bool:
        """Create the table, passing the layout to connectors that store
        one (memory — transactional with the table via snapshots); returns
        whether the connector took ownership of the layout."""
        import inspect

        kw = {}
        if layout is not None:
            try:
                if "layout" in inspect.signature(conn.create_table).parameters:
                    kw = {"layout": layout}
            except (TypeError, ValueError):  # builtins / C callables
                pass
        conn.create_table(schema, table, cols, **kw)
        return bool(kw)

    def _register_layout(self, cat, schema, table, layout, owned: bool) -> None:
        """Engine-level registry fallback for connectors that cannot store
        the layout themselves.  NOT transactional (a rolled-back CREATE
        leaves the entry until the matching DROP) — connector-owned layouts
        are preferred exactly because they roll back with the table."""
        if layout is not None and not owned:
            from trino_tpu.partitioning import declare_layout

            declare_layout(
                (cat, schema, table), layout.bucket_columns, layout.bucket_count
            )

    def _exec_CreateTable(self, stmt: ast.CreateTable) -> MaterializedResult:
        from trino_tpu import types as T
        from trino_tpu.connectors.api import ColumnMeta

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        self.access_control.check_can_write(self.user, cat, schema, table)
        if table in conn.metadata().list_tables(schema):
            if stmt.if_not_exists:
                return _ok("CREATE TABLE")
            raise ValueError(f"table '{cat}.{schema}.{table}' already exists")
        cols = [ColumnMeta(n, T.parse_type(t)) for n, t in stmt.columns]
        layout = self._table_layout_from(
            stmt.properties, [n for n, _ in stmt.columns]
        )
        self.transactions.notify_write(cat, schema, table)
        owned = self._create_with_layout(conn, schema, table, cols, layout)
        self._register_layout(cat, schema, table, layout, owned)
        self.grants.set_owner(cat, schema, table, self.user)
        return _ok("CREATE TABLE")

    def _exec_CreateTableAs(self, stmt: ast.CreateTableAs) -> MaterializedResult:
        from trino_tpu.connectors.api import ColumnMeta, TableHandle

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        self.access_control.check_can_write(self.user, cat, schema, table)
        if table in conn.metadata().list_tables(schema):
            if stmt.if_not_exists:
                return _ok("CREATE TABLE AS")
            raise ValueError(f"table '{cat}.{schema}.{table}' already exists")
        result = self._run_query(stmt.query)
        cols = [
            ColumnMeta(n, t) for n, t in zip(result.column_names, result.types)
        ]
        layout = self._table_layout_from(stmt.properties, result.column_names)
        self.transactions.notify_write(cat, schema, table)
        owned = self._create_with_layout(conn, schema, table, cols, layout)
        self._register_layout(cat, schema, table, layout, owned)
        self.grants.set_owner(cat, schema, table, self.user)
        self._write_rows(conn, TableHandle(cat, schema, table), result)
        return MaterializedResult(["rows"], [(result.row_count,)], [])

    def _exec_InsertStatement(self, stmt: ast.InsertStatement) -> MaterializedResult:
        from trino_tpu.connectors.api import TableHandle

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        meta = conn.metadata().table_metadata(schema, table)
        result = self._run_query(stmt.query)
        if stmt.columns:
            # align provided columns to table order, nulls elsewhere
            name_to_idx = {n: i for i, n in enumerate(stmt.columns)}
            reordered = []
            for r in result.rows:
                row = []
                for c in meta.columns:
                    i = name_to_idx.get(c.name)
                    row.append(None if i is None else r[i])
                reordered.append(tuple(row))
            result = MaterializedResult(
                [c.name for c in meta.columns], reordered,
                [c.type for c in meta.columns],
            )
        self.access_control.check_can_write(self.user, cat, schema, table)
        self.transactions.notify_write(cat, schema, table)
        self._write_rows(conn, TableHandle(cat, schema, table), result)
        return MaterializedResult(["rows"], [(result.row_count,)], [])

    def _exec_CreateView(self, stmt: ast.CreateView) -> MaterializedResult:
        key = self._resolve_table(stmt.name)
        if key in self.views and not stmt.or_replace:
            raise ValueError(f"view {'.'.join(stmt.name)} already exists")
        # validate with the NEW definition installed so a self-referencing
        # replacement trips the planner's recursion check, then roll back
        # on any validation failure
        missing = object()
        prev = self.views.get(key, missing)
        self.views[key] = stmt.query
        try:
            self.plan_query(stmt.query)
        except BaseException:
            if prev is missing:
                del self.views[key]
            else:
                self.views[key] = prev
            raise
        return _ok("CREATE VIEW")

    def _exec_DropView(self, stmt: ast.DropView) -> MaterializedResult:
        key = self._resolve_table(stmt.name)
        if key not in self.views:
            if stmt.if_exists:
                return _ok("DROP VIEW")
            raise KeyError(f"view {'.'.join(stmt.name)} does not exist")
        del self.views[key]
        return _ok("DROP VIEW")

    def _exec_PrepareStatement(self, stmt: ast.PrepareStatement) -> MaterializedResult:
        self.prepared[stmt.name] = stmt.text
        return _ok("PREPARE")

    def _exec_ExecuteStatement(self, stmt: ast.ExecuteStatement) -> MaterializedResult:
        from trino_tpu.dbapi import _substitute

        text = self.prepared.get(stmt.name)
        if text is None:
            raise KeyError(f"prepared statement {stmt.name} not found")
        params = [_ast_literal_value(p) for p in stmt.params]
        return self.execute(_substitute(text, params))

    def _exec_DescribeStatement(self, stmt: ast.DescribeStatement) -> MaterializedResult:
        """DESCRIBE INPUT/OUTPUT over a prepared statement (reference:
        sql/analyzer DescribeInputRewrite / DescribeOutputRewrite): the
        statement plans with placeholders bound to NULL; OUTPUT reports the
        result columns, INPUT the parameter positions (types unknown — the
        engine does not infer placeholder types, like the reference reports
        'unknown' for non-inferable positions)."""
        from trino_tpu import types as T
        from trino_tpu.dbapi import _substitute

        text = self.prepared.get(stmt.name)
        if text is None:
            raise KeyError(f"prepared statement {stmt.name} not found")
        n_params = text.count("?")
        if stmt.kind == "input":
            return MaterializedResult(
                ["Position", "Type"],
                [(i, "unknown") for i in range(n_params)],
                [T.BIGINT, T.VARCHAR],
            )
        bound = _substitute(text, [None] * n_params)
        parsed = parse_statement(bound)
        if not isinstance(parsed, ast.SelectStatement):
            raise NotImplementedError("DESCRIBE OUTPUT supports queries only")
        plan = self.plan_query(parsed.query)
        rows = [
            (name, sym.type.name)
            for name, sym in zip(plan.column_names, plan.symbols)
        ]
        return MaterializedResult(
            ["Column Name", "Type"], rows, [T.VARCHAR, T.VARCHAR]
        )

    def _exec_DeallocateStatement(
        self, stmt: ast.DeallocateStatement
    ) -> MaterializedResult:
        self.prepared.pop(stmt.name, None)
        return _ok("DEALLOCATE")

    def _exec_AlterTable(self, stmt: ast.AlterTable) -> MaterializedResult:
        """ALTER TABLE via snapshot + rebuild on write-capable connectors
        (reference roles: sql/tree/RenameTable/AddColumn/DropColumn/
        RenameColumn + connector metadata DDL methods)."""
        from trino_tpu import types as T
        from trino_tpu.connectors.api import ColumnMeta, TableHandle

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        if not conn.supports_writes():
            raise NotImplementedError(f"connector {cat} does not support ALTER")
        meta = conn.metadata().table_metadata(schema, table)
        self.access_control.check_can_write(self.user, cat, schema, table)
        self.transactions.notify_write(cat, schema, table)
        data = self._run_query(
            ast.Query(
                ast.QuerySpec(
                    (ast.Star(),), ast.TableRef((cat, schema, table)), None, (), None
                )
            )
        )
        cols = list(meta.columns)
        rows = [list(r) for r in data.rows]
        if stmt.action == "rename_table":
            # unqualified targets resolve against the SOURCE table's
            # catalog/schema (the reference renames within them)
            if len(stmt.target) == 1:
                tgt = (cat, schema, stmt.target[0])
            elif len(stmt.target) == 2:
                tgt = (cat, stmt.target[0], stmt.target[1])
            else:
                tgt = tuple(stmt.target)
            if tgt[0] != cat:
                raise ValueError("RENAME cannot move tables across catalogs")
            new_schema, new_table = tgt[1], tgt[2]
            existing = conn.metadata().list_tables(new_schema)
            if new_table in existing:
                raise ValueError(
                    f"target table {new_schema}.{new_table} already exists"
                )
            self.transactions.notify_write(cat, new_schema, new_table)
        else:
            new_schema, new_table = schema, table
            names = [c.name for c in cols]
            if stmt.action == "add_column":
                if stmt.column in names:
                    raise ValueError(f"column {stmt.column} already exists")
                cols.append(ColumnMeta(stmt.column, T.parse_type(stmt.column_type)))
                for r in rows:
                    r.append(None)
            elif stmt.action == "drop_column":
                if stmt.column not in names:
                    raise ValueError(f"column {stmt.column} does not exist")
                ix = names.index(stmt.column)
                cols.pop(ix)
                for r in rows:
                    r.pop(ix)
            elif stmt.action == "rename_column":
                if stmt.column not in names:
                    raise ValueError(f"column {stmt.column} does not exist")
                if stmt.new_name in names:
                    raise ValueError(
                        f"column {stmt.new_name} already exists"
                    )
                ix = names.index(stmt.column)
                cols[ix] = ColumnMeta(stmt.new_name, cols[ix].type)
            else:
                raise NotImplementedError(f"ALTER action {stmt.action}")
        result = MaterializedResult(
            [c.name for c in cols], [tuple(r) for r in rows], [c.type for c in cols]
        )
        same_name = (new_schema, new_table) == (schema, table)
        snap_fn = getattr(conn, "snapshot_table", None)
        snap = snap_fn(schema, table) if (same_name and snap_fn) else None
        conn.create_table(new_schema, new_table, cols)
        try:
            self._write_rows(conn, TableHandle(cat, new_schema, new_table), result)
        except BaseException:
            # never leave the table truncated/half-built
            if same_name and snap_fn is not None:
                conn.restore_table(schema, table, snap)
            elif not same_name:
                conn.drop_table(TableHandle(cat, new_schema, new_table))
            raise
        if not same_name:
            conn.drop_table(TableHandle(cat, schema, table))
            self.grants.set_owner(cat, new_schema, new_table, self.user)
        return _ok("ALTER TABLE")

    def _exec_GrantStatement(self, stmt: ast.GrantStatement) -> MaterializedResult:
        if stmt.roles:
            for r in stmt.roles:
                self.grants.grant_role(r, stmt.grantee)
            return _ok("GRANT ROLE")
        cat, schema, table = self._resolve_table(stmt.name)
        self.grants.grant(stmt.grantee, stmt.privileges, cat, schema, table)
        return _ok("GRANT")

    def _exec_RevokeStatement(self, stmt: ast.RevokeStatement) -> MaterializedResult:
        if stmt.roles:
            for r in stmt.roles:
                self.grants.revoke_role(r, stmt.grantee)
            return _ok("REVOKE ROLE")
        cat, schema, table = self._resolve_table(stmt.name)
        self.grants.revoke(stmt.grantee, stmt.privileges, cat, schema, table)
        return _ok("REVOKE")

    def _exec_RoleStatement(self, stmt: ast.RoleStatement) -> MaterializedResult:
        if stmt.action == "create":
            self.grants.create_role(stmt.role)
            return _ok("CREATE ROLE")
        self.grants.drop_role(stmt.role)
        return _ok("DROP ROLE")

    def _exec_DeleteStatement(self, stmt: ast.DeleteStatement) -> MaterializedResult:
        """DELETE = filtered table rewrite (reference roles: sql/tree/Delete
        .java + plan/TableDeleteNode.java; connector-pushdown deletes become
        a full rewrite here, exact under the same snapshot semantics as
        INSERT)."""
        from trino_tpu.connectors.api import TableHandle

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        if not conn.supports_writes():
            raise NotImplementedError(f"connector {cat} does not support DELETE")
        meta = conn.metadata().table_metadata(schema, table)
        self.access_control.check_can_delete(self.user, cat, schema, table)
        # rows to KEEP: predicate FALSE or NULL; bare DELETE keeps nothing
        if stmt.where is None:
            keep_where: ast.Node = ast.BooleanLiteral(False)
        else:
            keep_where = ast.UnaryOp(
                "not",
                ast.FunctionCall(
                    "coalesce", (stmt.where, ast.BooleanLiteral(False))
                ),
            )
        ref = ast.TableRef((cat, schema, table))
        kept = self._run_query(
            ast.Query(ast.QuerySpec((ast.Star(),), ref, keep_where, (), None))
        )
        total = conn.metadata().table_row_count(schema, table) if hasattr(
            conn.metadata(), "table_row_count"
        ) else None
        if total is None:
            total = self._run_query(
                ast.Query(
                    ast.QuerySpec(
                        (ast.SelectItem(
                            ast.FunctionCall("count", (), is_star=True)
                        ),),
                        ref, None, (), None,
                    )
                )
            ).rows[0][0]
        self.transactions.notify_write(cat, schema, table)
        self._rewrite_table(conn, cat, schema, table, meta, kept)
        return MaterializedResult(["rows"], [(total - kept.row_count,)], [])

    def _rewrite_table(self, conn, cat, schema, table, meta, result) -> None:
        """Crash-safe truncate+rewrite: the pre-image is captured first and
        restored if the write-back fails partway (DML must never leave the
        table truncated)."""
        from trino_tpu.connectors.api import TableHandle

        snap_fn = getattr(conn, "snapshot_table", None)
        snap = snap_fn(schema, table) if snap_fn is not None else None
        try:
            conn.create_table(schema, table, list(meta.columns))
            self._write_rows(conn, TableHandle(cat, schema, table), result)
        except BaseException:
            if snap_fn is not None:
                conn.restore_table(schema, table, snap)
            raise

    def _exec_MergeStatement(self, stmt: ast.MergeStatement) -> MaterializedResult:
        """MERGE = three rewrite queries stitched host-side (reference roles:
        sql/tree/Merge.java + planner MergeWriterNode + connector merge
        sinks):

          1. target LEFT-correlated: matched rows run the first WHEN MATCHED
             clause that fires (UPDATE projects new values, DELETE drops);
          2. target rows with no source match are kept verbatim;
          3. WHEN NOT MATCHED INSERT rows come from source rows with no
             target match.

        First-match-wins across clauses is a nested IF chain, exactly the
        searched-CASE the reference plans.  A target row matched by more than
        one source row is a cardinality violation (reference:
        MERGE_TARGET_ROW_MULTIPLE_MATCHES); detected by comparing the join
        pair count against the count of distinct matched target rows."""
        cat, schema, table = self._resolve_table(stmt.target)
        conn = self.catalogs.get(cat)
        if not conn.supports_writes():
            raise NotImplementedError(f"connector {cat} does not support MERGE")
        meta = conn.metadata().table_metadata(schema, table)
        self.access_control.check_can_update(self.user, cat, schema, table)
        self.access_control.check_can_write(self.user, cat, schema, table)
        ta = stmt.target_alias or table
        tgt_rel: ast.Node = ast.AliasedRelation(
            ast.TableRef((cat, schema, table)), ta
        )
        if isinstance(stmt.source, ast.Query):
            src_rel: ast.Node = ast.SubqueryRelation(stmt.source)
        else:
            src_rel = stmt.source
        if stmt.source_alias:
            src_rel = ast.AliasedRelation(src_rel, stmt.source_alias)

        def chain(cases, leaf_fn, else_expr):
            """First-match-wins nested IF over WHEN clauses."""
            out = else_expr
            for c in reversed(cases):
                cond = c.condition if c.condition is not None else ast.BooleanLiteral(True)
                out = ast.FunctionCall("if", (cond, leaf_fn(c), out))
            return out

        matched_cases = [c for c in stmt.cases if c.matched]
        insert_cases = [c for c in stmt.cases if not c.matched]

        # -- part 1: matched target rows through the WHEN MATCHED chain ------
        matched_rows: list = []
        n_matched_actioned = 0
        if matched_cases:
            items = []
            for col in meta.columns:
                ref = ast.Identifier((ta, col.name))

                def leaf(c, col=col, ref=ref):
                    if c.action == "delete":
                        return ast.CastExpr(ast.NullLiteral(), col.type.name)
                    assigns = dict(c.assignments)
                    if col.name in assigns:
                        return ast.CastExpr(assigns[col.name], col.type.name)
                    return ref

                items.append(ast.SelectItem(chain(matched_cases, leaf, ref), alias=col.name))
            # __keep: FALSE when the first firing clause is DELETE;
            # __hit: TRUE when any clause fired (for the affected-row count)
            items.append(
                ast.SelectItem(
                    chain(
                        matched_cases,
                        lambda c: ast.BooleanLiteral(c.action != "delete"),
                        ast.BooleanLiteral(True),
                    ),
                    alias="__keep",
                )
            )
            items.append(
                ast.SelectItem(
                    chain(
                        matched_cases,
                        lambda c: ast.BooleanLiteral(True),
                        ast.BooleanLiteral(False),
                    ),
                    alias="__hit",
                )
            )
            join = ast.Join("inner", tgt_rel, src_rel, stmt.on)
            res = self._run_query(
                ast.Query(ast.QuerySpec(tuple(items), join, None, (), None))
            )
            n_join_pairs = len(res.rows)
            for r in res.rows:
                keep, hit = r[-2], r[-1]
                if hit:
                    n_matched_actioned += 1
                if keep:
                    matched_rows.append(tuple(r[:-2]))
        else:
            # no matched clauses: matched target rows stay unchanged; fold
            # them into part 2 by keeping ALL target rows there instead
            pass

        # -- part 2: target rows without any source match ---------------------
        exists_q = ast.Query(
            ast.QuerySpec(
                (ast.SelectItem(ast.NumberLiteral("1")),),
                src_rel,
                stmt.on,
                (),
                None,
            )
        )
        not_matched_where = (
            ast.UnaryOp("not", ast.Exists(exists_q)) if matched_cases else None
        )
        kept = self._run_query(
            ast.Query(
                ast.QuerySpec(
                    (ast.Star(),), tgt_rel, not_matched_where, (), None
                )
            )
        )
        if matched_cases:
            # Cardinality check: part 1 emitted one row per (target, source)
            # join pair.  #pairs > #matched-target-rows means some target
            # row was matched by >1 source row.
            n_target = self._run_query(
                ast.Query(
                    ast.QuerySpec(
                        (
                            ast.SelectItem(
                                ast.FunctionCall("count", (), is_star=True)
                            ),
                        ),
                        tgt_rel,
                        None,
                        (),
                        None,
                    )
                )
            ).rows[0][0]
            n_matched = int(n_target) - len(kept.rows)
            if n_join_pairs > n_matched:
                raise ValueError(
                    "MERGE: one target table row matched more than one "
                    "source row (MERGE_TARGET_ROW_MULTIPLE_MATCHES)"
                )

        # -- part 3: WHEN NOT MATCHED inserts ---------------------------------
        insert_rows: list = []
        if insert_cases:
            tgt_exists = ast.Query(
                ast.QuerySpec(
                    (ast.SelectItem(ast.NumberLiteral("1")),),
                    tgt_rel,
                    stmt.on,
                    (),
                    None,
                )
            )
            items = []
            for col in meta.columns:

                def leaf_ins(c, col=col):
                    cols = list(c.columns) or [m.name for m in meta.columns]
                    if col.name in cols:
                        v = c.assignments[cols.index(col.name)]
                        return ast.CastExpr(v, col.type.name)
                    return ast.CastExpr(ast.NullLiteral(), col.type.name)

                items.append(
                    ast.SelectItem(
                        chain(
                            insert_cases,
                            leaf_ins,
                            ast.CastExpr(ast.NullLiteral(), col.type.name),
                        ),
                        alias=col.name,
                    )
                )
            items.append(
                ast.SelectItem(
                    chain(
                        insert_cases,
                        lambda c: ast.BooleanLiteral(True),
                        ast.BooleanLiteral(False),
                    ),
                    alias="__hit",
                )
            )
            res = self._run_query(
                ast.Query(
                    ast.QuerySpec(
                        tuple(items),
                        src_rel,
                        ast.UnaryOp("not", ast.Exists(tgt_exists)),
                        (),
                        None,
                    )
                )
            )
            for r in res.rows:
                if r[-1]:
                    insert_rows.append(tuple(r[:-1]))

        all_rows = matched_rows + list(kept.rows) + insert_rows
        combined = MaterializedResult(
            [c.name for c in meta.columns],
            all_rows,
            [c.type for c in meta.columns],
        )
        self.transactions.notify_write(cat, schema, table)
        self._rewrite_table(conn, cat, schema, table, meta, combined)
        return MaterializedResult(
            ["rows"], [(n_matched_actioned + len(insert_rows),)], []
        )

    def _exec_UpdateStatement(self, stmt: ast.UpdateStatement) -> MaterializedResult:
        """UPDATE = per-column conditional rewrite (reference:
        sql/tree/Update.java + plan/MergeWriterNode.java roles)."""
        from trino_tpu.connectors.api import TableHandle

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        if not conn.supports_writes():
            raise NotImplementedError(f"connector {cat} does not support UPDATE")
        meta = conn.metadata().table_metadata(schema, table)
        assigns = dict(stmt.assignments)
        unknown = set(assigns) - {c.name for c in meta.columns}
        if unknown:
            raise ValueError(f"unknown columns in UPDATE: {sorted(unknown)}")
        self.access_control.check_can_update(self.user, cat, schema, table)
        cond = (
            ast.FunctionCall("coalesce", (stmt.where, ast.BooleanLiteral(False)))
            if stmt.where is not None
            else ast.BooleanLiteral(True)
        )
        items = []
        for c in meta.columns:
            ref = ast.Identifier((c.name,))
            if c.name in assigns:
                # the assigned value is cast to the COLUMN's declared type
                # (never the other way round: the stored payload must match
                # the table metadata)
                val = ast.CastExpr(assigns[c.name], c.type.name)
                items.append(
                    ast.SelectItem(
                        ast.FunctionCall("if", (cond, val, ref)),
                        alias=c.name,
                    )
                )
            else:
                items.append(ast.SelectItem(ref, alias=c.name))
        tref = ast.TableRef((cat, schema, table))
        rewritten = self._run_query(
            ast.Query(ast.QuerySpec(tuple(items), tref, None, (), None))
        )
        touched = self._run_query(
            ast.Query(
                ast.QuerySpec(
                    (ast.SelectItem(
                        ast.FunctionCall("count", (), is_star=True)
                    ),),
                    tref, stmt.where, (), None,
                )
            )
        ).rows[0][0]
        self.transactions.notify_write(cat, schema, table)
        self._rewrite_table(conn, cat, schema, table, meta, rewritten)
        return MaterializedResult(["rows"], [(touched,)], [])

    def _exec_DropTable(self, stmt: ast.DropTable) -> MaterializedResult:
        from trino_tpu.connectors.api import TableHandle

        cat, schema, table = self._resolve_table(stmt.name)
        conn = self.catalogs.get(cat)
        if stmt.if_exists and table not in conn.metadata().list_tables(schema):
            return _ok("DROP TABLE")
        self.access_control.check_can_write(self.user, cat, schema, table)
        self.transactions.notify_write(cat, schema, table)
        conn.drop_table(TableHandle(cat, schema, table))
        from trino_tpu.partitioning import drop_layout

        drop_layout((cat, schema, table))
        return _ok("DROP TABLE")

    def _write_rows(self, conn, handle, result: MaterializedResult) -> None:
        """Scaled writers (reference: the scaled-writer operators behind
        task_writer_count): page building — the host-CPU-heavy part — runs
        on `writer_count` threads over row chunks; sink commits are
        serialized (connector sinks need no internal locking)."""
        from concurrent.futures import ThreadPoolExecutor

        from trino_tpu.columnar.builders import column_from_values
        from trino_tpu.connectors.api import ColumnData

        meta = conn.metadata().table_metadata(handle.schema, handle.table)
        sink = conn.page_sink(
            handle, [c.name for c in meta.columns], [c.type for c in meta.columns]
        )
        if not result.rows:
            return
        writers = max(1, int(self.properties.get("writer_count") or 1))

        def build(i_cm):
            i, cm = i_cm
            col = column_from_values([r[i] for r in result.rows], cm.type)
            return ColumnData(col.data, col.valid, col.dictionary)

        items = list(enumerate(meta.columns))
        if writers <= 1 or len(items) <= 1 or len(result.rows) < 1024:
            cols = [build(x) for x in items]
        else:
            # column-parallel build keeps dictionaries whole and the commit
            # single (one sink append = one snapshot, iceberg-compatible)
            with ThreadPoolExecutor(max_workers=min(writers, len(items))) as pool:
                cols = list(pool.map(build, items))
        sink.append(cols)


def _values_relation(rows, types, names=None):
    """Materialized python rows -> a VALUES relation of typed literal AST
    nodes (the recursive-CTE binding; reference: the VALUES node the
    reference's CTE expansion feeds each iteration)."""
    import datetime
    from decimal import Decimal

    from trino_tpu import types as T

    def lit(v, t):
        if v is None:
            return ast.CastExpr(ast.NullLiteral(), t.name)
        if t is T.BOOLEAN or isinstance(v, bool):
            return ast.BooleanLiteral(bool(v))
        if isinstance(v, Decimal):
            return ast.CastExpr(ast.NumberLiteral(str(v)), t.name)
        if isinstance(v, datetime.datetime):
            return ast.TimestampLiteral(v.isoformat(sep=" "))
        if isinstance(v, datetime.date):
            return ast.DateLiteral(v.isoformat())
        if isinstance(v, str):
            return ast.CastExpr(ast.StringLiteral(v), t.name) if not T.is_string_kind(t) else ast.StringLiteral(v)
        if isinstance(v, float):
            return ast.CastExpr(ast.NumberLiteral(repr(v)), t.name)
        if isinstance(v, int):
            return ast.CastExpr(ast.NumberLiteral(str(v)), t.name)
        raise NotImplementedError(
            f"recursive CTE value of type {type(v).__name__}"
        )

    if not rows:
        # zero-row relation with the right arity/types: typed NULLs under
        # WHERE false (VALUES itself needs >= 1 row)
        items = tuple(
            ast.SelectItem(
                ast.CastExpr(ast.NullLiteral(), t.name),
                alias=(names[i] if names else f"c{i}"),
            )
            for i, t in enumerate(types)
        )
        return ast.QuerySpec(
            items, None, ast.BooleanLiteral(False), (), None
        )
    return ast.ValuesRelation(
        tuple(tuple(lit(v, t) for v, t in zip(r, types)) for r in rows)
    )


def _ast_literal_value(node):
    """EXECUTE ... USING parameter -> python literal value."""
    if isinstance(node, ast.NumberLiteral):
        txt = node.text
        return float(txt) if ("." in txt or "e" in txt.lower()) else int(txt)
    if isinstance(node, ast.StringLiteral):
        return node.value
    if isinstance(node, ast.BooleanLiteral):
        return node.value
    if isinstance(node, ast.NullLiteral):
        return None
    if isinstance(node, ast.UnaryOp) and node.op == "-":
        v = _ast_literal_value(node.operand)
        return -v
    raise ValueError(
        f"EXECUTE USING supports literal parameters only, got "
        f"{type(node).__name__}"
    )


def _ok(tag: str) -> MaterializedResult:
    return MaterializedResult([tag], [(True,)], [])
