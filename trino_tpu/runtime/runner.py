"""In-process query runner: SQL text -> materialized results.

Reference role: testing/LocalQueryRunner.java:260 — the full
parse -> analyze -> plan -> execute pipeline in one process, no RPC; results
captured the way PageConsumerOperator captures pages.  This is both the test
harness entry point and the kernel of the single-node engine.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from trino_tpu.connectors.api import CatalogManager, default_catalogs
from trino_tpu.planner.logical_planner import LogicalPlanner, Session
from trino_tpu.planner.plan import OutputNode, plan_text
from trino_tpu.runtime.local_planner import LocalExecutionPlanner
from trino_tpu.sql import ast
from trino_tpu.sql.parser import parse_statement


@dataclass
class MaterializedResult:
    """Reference role: testing/MaterializedResult.java."""

    column_names: list
    rows: list  # list of tuples of python values
    types: list

    @property
    def row_count(self) -> int:
        return len(self.rows)

    def only_value(self):
        assert len(self.rows) == 1 and len(self.rows[0]) == 1, self.rows
        return self.rows[0][0]

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame(self.rows, columns=self.column_names)


class LocalQueryRunner:
    def __init__(
        self,
        catalogs: Optional[CatalogManager] = None,
        catalog: str = "tpch",
        schema: str = "tiny",
        target_splits: int = 4,
    ):
        self.catalogs = catalogs or default_catalogs()
        self.session = Session(catalog, schema)
        self.target_splits = target_splits

    # -- planning -------------------------------------------------------------

    def create_plan(self, sql: str) -> OutputNode:
        stmt = parse_statement(sql)
        if not isinstance(stmt, ast.SelectStatement):
            raise NotImplementedError(f"statement: {type(stmt).__name__}")
        plan = LogicalPlanner(self.catalogs, self.session).plan(stmt.query)
        return self.optimize(plan)

    def optimize(self, plan: OutputNode) -> OutputNode:
        from trino_tpu.planner.optimizer import optimize

        return optimize(plan, catalogs=self.catalogs)

    def explain(self, sql: str) -> str:
        return plan_text(self.create_plan(sql))

    # -- execution ------------------------------------------------------------

    def execute(self, sql: str) -> MaterializedResult:
        plan = self.create_plan(sql)
        physical = LocalExecutionPlanner(
            self.catalogs, target_splits=self.target_splits
        ).plan(plan)
        rows = []
        for batch in physical.stream:
            rows.extend(tuple(r) for r in batch.to_pylist())
        return MaterializedResult(
            list(plan.column_names), rows, [s.type for s in plan.symbols]
        )
