"""Global function registry: one queryable catalog of every function the
engine resolves.

Reference roles: metadata/GlobalFunctionCatalog.java + FunctionListBuilder
(the source of SHOW FUNCTIONS and information_schema-style listings) and the
function SPI registration path (spi/function/FunctionProvider — connectors
contribute functions at catalog registration).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass(frozen=True)
class FunctionMetadata:
    name: str
    kind: str  # scalar | aggregate | window | table
    return_type: str
    argument_types: tuple = ()
    deterministic: bool = True
    description: str = ""


_DESCRIPTIONS = {
    "abs": "absolute value",
    "avg": "arithmetic mean",
    "cardinality": "number of elements in an array",
    "coalesce": "first non-null argument",
    "concat": "string concatenation",
    "contains": "true if array contains value",
    "count": "row count",
    "element_at": "array element at index (NULL out of range)",
    "json_extract": "JSON subtree at a JSONPath",
    "json_extract_scalar": "JSON scalar at a JSONPath as varchar",
    "length": "string length",
    "lower": "lowercase",
    "map": "map from a key array and a value array",
    "map_keys": "keys of a map as an array",
    "map_values": "values of a map as an array",
    "map_concat": "union of maps (later maps win on duplicate keys)",
    "max": "maximum",
    "min": "minimum",
    "regexp_like": "true if the string matches the regex",
    "round": "round to given digits",
    "sequence": "array of integers from start to stop",
    "split": "split string by delimiter into an array",
    "stddev": "sample standard deviation",
    "substr": "substring",
    "sum": "sum",
    "upper": "uppercase",
}

#: window-only functions (the planner's _WindowExtractor set)
WINDOW_FUNCS = (
    "row_number",
    "rank",
    "dense_rank",
    "percent_rank",
    "cume_dist",
    "ntile",
    "lag",
    "lead",
    "first_value",
    "last_value",
    "nth_value",
)


class FunctionRegistry:
    """Name -> FunctionMetadata rows; engine built-ins plus connector
    contributions (register_connector_functions)."""

    def __init__(self):
        self._functions: dict[tuple, FunctionMetadata] = {}
        self._load_builtins()

    # -- registration --------------------------------------------------------

    def register(self, meta: FunctionMetadata) -> None:
        self._functions[(meta.name, meta.argument_types)] = meta

    def register_connector_functions(self, connector) -> None:
        """SPI hook: connectors may expose `functions() -> [FunctionMetadata]`
        (reference: spi/function/FunctionProvider.getFunctions)."""
        fns = getattr(connector, "functions", None)
        if fns is None:
            return
        for meta in fns():
            self.register(meta)

    # -- queries -------------------------------------------------------------

    def list(self) -> list:
        return sorted(
            self._functions.values(), key=lambda m: (m.name, m.argument_types)
        )

    def lookup(self, name: str) -> list:
        return [m for m in self.list() if m.name == name]

    # -- built-ins -----------------------------------------------------------

    def _load_builtins(self) -> None:
        from trino_tpu.planner.functions import AGG_FUNCS, SCALAR_RESULT
        from trino_tpu import types as T

        for name in sorted(SCALAR_RESULT):
            if name.startswith("$"):
                continue  # operators, not callable by name
            try:
                rt = SCALAR_RESULT[name]([T.DOUBLE, T.DOUBLE, T.DOUBLE]).name
            except Exception:
                rt = "same as input"
            self.register(
                FunctionMetadata(
                    name,
                    "scalar",
                    rt,
                    description=_DESCRIPTIONS.get(name, ""),
                )
            )
        for name in sorted(AGG_FUNCS):
            self.register(
                FunctionMetadata(
                    name,
                    "aggregate",
                    "same as input" if name in ("min", "max", "sum") else "bigint/double",
                    description=_DESCRIPTIONS.get(name, ""),
                )
            )
        for name in WINDOW_FUNCS:
            self.register(
                FunctionMetadata(
                    name,
                    "window",
                    "bigint",
                    description=_DESCRIPTIONS.get(name, ""),
                )
            )
        from trino_tpu.planner.table_functions import TABLE_FUNCTIONS

        for name, tf in sorted(TABLE_FUNCTIONS.items()):
            self.register(
                FunctionMetadata(
                    name,
                    "table",
                    "table",
                    description=tf.description,
                )
            )


_REGISTRY: Optional[FunctionRegistry] = None


def global_registry() -> FunctionRegistry:
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = FunctionRegistry()
    return _REGISTRY
