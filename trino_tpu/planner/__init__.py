"""Logical planning (reference: core/trino-main/.../sql/planner)."""

from trino_tpu.planner.plan import *  # noqa: F401,F403
