"""Cross-join elimination + predicate pushdown into join criteria.

Reference roles: sql/planner/optimizations/PredicatePushDown.java,
iterative/rule/EliminateCrossJoins.java, and the join-distribution side of
ReorderJoins — comma-list FROM clauses plan as cross joins under one big
filter; this pass flattens the cross tree, classifies conjuncts
(single-source / equi-pair / residual), pushes single-source predicates down,
and greedily rebuilds an equi-join tree ordered by estimated cardinality
(largest relation stays the streamed probe side; smaller connected relations
become materialized build sides, matching the TPU hash-join operator which
fully materializes its build input in HBM).
"""

from __future__ import annotations

from collections import defaultdict

from trino_tpu.expr.ir import Call, Expr, Form, SpecialForm, SymbolRef, and_
from trino_tpu.planner import plan as P
from trino_tpu.planner.stats import estimate_rows


def split_conjuncts_ir(e: Expr) -> list:
    if isinstance(e, SpecialForm) and e.form == Form.AND:
        out = []
        for a in e.args:
            out.extend(split_conjuncts_ir(a))
        return out
    return [e]


def collect_symbol_names(e: Expr, acc=None, _seen=None) -> set:
    if acc is None:
        acc = set()
    if _seen is None:
        _seen = set()
    if id(e) in _seen:  # shared-DAG guard (see ir.visit)
        return acc
    _seen.add(id(e))
    if isinstance(e, SymbolRef):
        acc.add(e.name)
    for k in e.children():
        collect_symbol_names(k, acc, _seen)
    return acc


def _flatten_cross(node: P.PlanNode, sources: list) -> None:
    if isinstance(node, P.JoinNode) and node.kind == "cross" and node.filter is None:
        _flatten_cross(node.left, sources)
        _flatten_cross(node.right, sources)
    else:
        sources.append(node)


def _equi_edge(c: Expr, sym2src: dict):
    """(src_i, sym_i, src_j, sym_j) if c is `a = b` with a,b plain symbols of
    two different sources."""
    if not (isinstance(c, Call) and c.name == "$eq" and len(c.args) == 2):
        return None
    a, b = c.args
    if not (isinstance(a, SymbolRef) and isinstance(b, SymbolRef)):
        return None
    sa, sb = sym2src.get(a.name), sym2src.get(b.name)
    if sa is None or sb is None or sa == sb:
        return None
    return (sa, P.Symbol(a.name, a.type), sb, P.Symbol(b.name, b.type))


def extract_common_or_conjuncts(e: Expr, _memo: dict = None) -> Expr:
    """OR(a AND b AND x1, a AND b AND x2) -> a AND b AND OR(x1, x2).

    Reference: sql/planner/iterative/rule/ExtractCommonPredicatesExpression
    Rewriter — without this, TPC-DS Q13/Q48-style predicates keep their join
    equalities trapped inside OR disjuncts and the comma join list degrades
    to a cross product.  Memoized by node identity (shared-DAG guard)."""
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(e))
    if hit is not None:
        return hit
    out = _extract_common_uncached(e, _memo)
    _memo[id(e)] = out
    return out


def _extract_common_uncached(e: Expr, _memo: dict) -> Expr:
    kids = e.children()
    if kids:
        e = e.with_children([extract_common_or_conjuncts(k, _memo) for k in kids])
    if not (isinstance(e, SpecialForm) and e.form == Form.OR):
        return e
    arms = [split_conjuncts_ir(a) for a in e.args]
    common_keys = set(c.key() for c in arms[0])
    for arm in arms[1:]:
        common_keys &= {c.key() for c in arm}
    if not common_keys:
        return e
    common = [c for c in arms[0] if c.key() in common_keys]
    rests = []
    for arm in arms:
        rest = [c for c in arm if c.key() not in common_keys]
        rests.append(and_(*rest) if rest else None)
    if any(r is None for r in rests):
        # one arm had ONLY common conjuncts: the OR reduces to them
        return and_(*common)
    from trino_tpu.expr.ir import or_

    return and_(*(common + [or_(*rests)]))


def eliminate_cross_joins(node: P.PlanNode, catalogs=None):
    """Filter(cross-join tree) -> pushed filters + greedy equi-join tree.
    Returns a replacement node or None."""
    if not isinstance(node, P.FilterNode):
        return None
    if not (
        isinstance(node.source, P.JoinNode)
        and node.source.kind == "cross"
        and node.source.filter is None
    ):
        return None
    sources: list = []
    _flatten_cross(node.source, sources)
    if len(sources) < 2:
        return None
    sym2src = {
        s.name: i for i, src in enumerate(sources) for s in src.outputs
    }
    single = defaultdict(list)
    edges = []  # (i, sym_i, j, sym_j, conjunct)
    residual = []
    predicate = extract_common_or_conjuncts(node.predicate)
    for c in split_conjuncts_ir(predicate):
        refs = collect_symbol_names(c)
        srcs = {sym2src[r] for r in refs if r in sym2src}
        if not srcs:
            residual.append(c)
            continue
        if len(srcs) == 1:
            single[next(iter(srcs))].append(c)
            continue
        edge = _equi_edge(c, sym2src)
        if edge is not None:
            edges.append(edge)
        else:
            residual.append(c)
    if not single and not edges:
        # nothing to push or join on — rebuilding would be a no-op and the
        # rewrite loop would never terminate
        return None
    for i, cs in single.items():
        sources[i] = P.FilterNode(sources[i], and_(*cs))

    if len(sources) <= MAX_REORDERED_JOINS:
        tree = _dp_join_order(sources, edges, catalogs)
    else:
        tree = _greedy_join_order(sources, edges, catalogs)
    out: P.PlanNode = tree
    if residual:
        out = P.FilterNode(out, and_(*residual))
    return out


#: DP join-order enumeration bound (reference: SystemSessionProperties
#: MAX_REORDERED_JOINS default 9 — beyond it ReorderJoins bails to the
#: syntactic order; we bail to the greedy heuristic instead)
MAX_REORDERED_JOINS = 9


def _edge_selectivity(si: str, sj: str, stats_i, stats_j) -> float:
    """1/max(ndv, ndv) per JoinStatsRule.calculateJoinSelectivity."""
    ni = stats_i.col(si).ndv
    nj = stats_j.col(sj).ndv
    m = max(ni or 0.0, nj or 0.0)
    if m:
        return 1.0 / m
    return 1.0 / max(stats_i.rows, stats_j.rows, 1.0)


def _dp_join_order(sources, edges, catalogs):
    """Bushy-tree DP over connected sub-plans, minimizing C_out (sum of
    intermediate result rows).  Reference role: iterative/rule/ReorderJoins
    (JoinEnumerator.chooseJoinOrder over set partitions, pruned by
    CostComparator) — same search space, simpler additive cost.

    Orientation: bigger side left (streamed probe), smaller side right
    (materialized build) — matching the TPU hash-join operator, which fully
    materializes its right input in HBM."""
    from trino_tpu.planner.stats import compute_stats

    n = len(sources)
    base = [compute_stats(s, catalogs) for s in sources]
    # rows per subset computed from base rows x crossing-edge selectivities
    edge_by_pair: dict = {}
    for (i, si, j, sj) in edges:
        sel = _edge_selectivity(si.name, sj.name, base[i], base[j])
        edge_by_pair.setdefault(frozenset((i, j)), []).append(sel)

    def subset_rows(mask: int) -> float:
        rows = 1.0
        mem = []
        for k in range(n):
            if mask >> k & 1:
                rows *= max(base[k].rows, 1.0)
                mem.append(k)
        for a_i in range(len(mem)):
            for b_i in range(a_i + 1, len(mem)):
                sels = edge_by_pair.get(frozenset((mem[a_i], mem[b_i])))
                if sels:
                    # dampen clauses beyond the first (correlated keys)
                    for x, s in enumerate(sorted(sels)):
                        rows *= s ** (1.0 if x == 0 else 0.5 ** x)
        return max(rows, 1.0)

    rows_of = {1 << k: max(base[k].rows, 1.0) for k in range(n)}
    # best[mask] = (cost, tree)
    best: dict = {1 << k: (0.0, sources[k]) for k in range(n)}

    def crossing_criteria(amask: int, bmask: int):
        crit = []
        for (i, si, j, sj) in edges:
            if (amask >> i & 1) and (bmask >> j & 1):
                crit.append((si, sj))
            elif (amask >> j & 1) and (bmask >> i & 1):
                crit.append((sj, si))
        return crit

    full = (1 << n) - 1
    for mask in range(3, full + 1):
        if mask & (mask - 1) == 0:  # singleton
            continue
        rows = subset_rows(mask)
        rows_of[mask] = rows
        best_here = None
        # enumerate proper sub-splits (canonical: a contains lowest bit)
        low = mask & -mask
        sub = (mask - 1) & mask
        while sub:
            a = sub
            b = mask ^ a
            if a & low and a in best and b in best:
                crit = crossing_criteria(a, b)
                # only consider connected splits unless nothing connects
                ca, ta = best[a]
                cb, tb = best[b]
                penalty = 0.0 if crit else rows_of[a] * rows_of[b]
                # probe = bigger side stays left
                if rows_of[a] >= rows_of[b]:
                    lm, rm = a, b
                else:
                    lm, rm = b, a
                    crit = [(sj, si) for si, sj in crit]
                cost = ca + cb + rows + 0.3 * rows_of[rm] + penalty
                if best_here is None or cost < best_here[0]:
                    kind = "inner" if crit else "cross"
                    best_here = (
                        cost,
                        P.JoinNode(kind, best[lm][1], best[rm][1], crit),
                    )
            sub = (sub - 1) & mask
        if best_here is not None:
            best[mask] = best_here
    return best[full][1]


def _greedy_join_order(sources, edges, catalogs):
    """Fallback beyond the DP bound: largest relation is the probe spine;
    repeatedly join the smallest relation connected to the joined set."""
    est = [estimate_rows(s, catalogs) for s in sources]
    start = max(range(len(sources)), key=est.__getitem__)
    joined = {start}
    tree = sources[start]
    pending = list(edges)
    while len(joined) < len(sources):
        connected = set()
        for (i, _, j, _) in [(e[0], e[1], e[2], e[3]) for e in pending]:
            if (i in joined) != (j in joined):
                connected.add(j if i in joined else i)
        if connected:
            cand = min(connected, key=est.__getitem__)
        else:
            cand = min(
                (k for k in range(len(sources)) if k not in joined),
                key=est.__getitem__,
            )
        criteria = []
        rest_edges = []
        for e in pending:
            i, si, j, sj = e
            if i in joined and j == cand:
                criteria.append((si, sj))
            elif j in joined and i == cand:
                criteria.append((sj, si))
            else:
                rest_edges.append(e)
        pending = rest_edges
        if criteria:
            tree = P.JoinNode("inner", tree, sources[cand], criteria)
        else:
            tree = P.JoinNode("cross", tree, sources[cand], [])
        joined.add(cand)
    assert not pending, f"unconsumed join edges: {pending}"
    return tree


def push_filter_through_semijoin(node: P.PlanNode):
    """Filter conjuncts not referencing the semi-join mark move below the
    SemiJoinNode onto its source (reference: PredicatePushDown's semi-join
    handling) — unlocking cross-join elimination underneath."""
    if not (isinstance(node, P.FilterNode) and isinstance(node.source, P.SemiJoinNode)):
        return None
    semi = node.source
    src_names = {s.name for s in semi.source.outputs}
    below, above = [], []
    for c in split_conjuncts_ir(node.predicate):
        refs = collect_symbol_names(c)
        if semi.mark.name not in refs and refs <= src_names:
            below.append(c)
        else:
            above.append(c)
    if not below:
        return None
    new_semi = P.SemiJoinNode(
        P.FilterNode(semi.source, and_(*below)),
        semi.filtering,
        semi.source_key,
        semi.filtering_key,
        semi.mark,
        semi.filter,
        semi.null_aware,
    )
    if above:
        return P.FilterNode(new_semi, and_(*above))
    return new_semi


def push_filter_through_join(node: P.PlanNode):
    """Filter(inner Join) -> push single-side conjuncts into the inputs and
    plain equi conjuncts into the criteria (PredicatePushDown for already-
    formed joins, e.g. JOIN ... ON plus WHERE conjuncts)."""
    if not (isinstance(node, P.FilterNode) and isinstance(node.source, P.JoinNode)):
        return None
    join = node.source
    if join.kind not in ("inner", "cross"):
        return None
    left_names = {s.name for s in join.left.outputs}
    right_names = {s.name for s in join.right.outputs}
    to_left, to_right, criteria, keep = [], [], [], []
    for c in split_conjuncts_ir(node.predicate):
        refs = collect_symbol_names(c)
        if refs <= left_names:
            to_left.append(c)
        elif refs <= right_names:
            to_right.append(c)
        else:
            sym2src = {n: 0 for n in left_names}
            sym2src.update({n: 1 for n in right_names})
            edge = _equi_edge(c, sym2src)
            if edge is not None:
                i, si, j, sj = edge
                criteria.append((si, sj) if i == 0 else (sj, si))
            else:
                keep.append(c)
    if not (to_left or to_right or criteria):
        return None
    left = P.FilterNode(join.left, and_(*to_left)) if to_left else join.left
    right = P.FilterNode(join.right, and_(*to_right)) if to_right else join.right
    kind = "inner" if (join.criteria or criteria) else join.kind
    new_join = P.JoinNode(
        kind, left, right, list(join.criteria) + criteria, join.filter,
        join.distribution,
    )
    if keep:
        return P.FilterNode(new_join, and_(*keep))
    return new_join
