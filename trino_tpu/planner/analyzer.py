"""Scoped expression analysis: AST -> typed IR over SymbolRefs.

Reference roles: sql/analyzer/ExpressionAnalyzer.java (typing/resolution) and
sql/planner/TranslationMap (the pluggable `hook` lets the aggregation planner
map group-by expressions and aggregate calls to their computed symbols, which
is exactly TranslationMap's job).
"""

from __future__ import annotations

import datetime
from decimal import Decimal
from typing import Callable, Optional

from trino_tpu import types as T
from trino_tpu.expr import ir
from trino_tpu.expr.ir import Call, Expr, Form, Literal, SpecialForm, SymbolRef
from trino_tpu.planner import plan as P
from trino_tpu.planner.functions import (
    AGG_FUNCS,
    arith_result_type,
    scalar_result_type,
)
from trino_tpu.sql import ast


class AnalysisError(ValueError):
    pass


class Field:
    __slots__ = ("name", "symbol", "alias", "source_name", "source_expr")

    def __init__(
        self,
        name: str,
        symbol: P.Symbol,
        alias: Optional[str] = None,
        source_name: Optional[str] = None,
        source_expr=None,
    ):
        self.name = name
        self.symbol = symbol
        self.alias = alias
        #: original column name when the item renamed a plain `t.col`
        #: (ORDER BY `t.col` must still match the renamed output)
        self.source_name = source_name
        #: the select item's source AST — ORDER BY may repeat an output
        #: item's full expression (`ORDER BY substr(s_city, 1, 30)`); frozen
        #: dataclass equality gives the structural match
        self.source_expr = source_expr

    def __repr__(self):  # pragma: no cover
        return f"{self.alias or ''}.{self.name}->{self.symbol.name}"


class Scope:
    """Name resolution scope with outer parent for correlation
    (reference: sql/analyzer/Scope.java)."""

    def __init__(self, fields: list[Field], parent: Optional["Scope"] = None):
        self.fields = fields
        self.parent = parent

    def resolve(self, parts: tuple) -> tuple[P.Symbol, bool]:
        """Returns (symbol, is_outer)."""
        sym = self._resolve_local(parts)
        if sym is not None:
            return sym, False
        if self.parent is not None:
            s, _ = self.parent.resolve(parts)
            return s, True
        raise AnalysisError(f"column not found: {'.'.join(parts)}")

    def _resolve_local(self, parts: tuple) -> Optional[P.Symbol]:
        if len(parts) == 1:
            matches = [f for f in self.fields if f.name == parts[0]]
        elif len(parts) == 2:
            matches = [
                f for f in self.fields if f.name == parts[1] and f.alias == parts[0]
            ]
        else:
            return None
        if len(matches) > 1:
            raise AnalysisError(f"ambiguous column: {'.'.join(parts)}")
        return matches[0].symbol if matches else None


_EPOCH = datetime.date(1970, 1, 1)


def _branch_cast(e: Expr, rt: T.Type) -> Expr:
    """Unify a conditional branch's REPRESENTATION with the result type.
    Decimal cents sitting next to doubles must descale through a real CAST
    — relabeling the channel would be off by 10^scale."""
    if (
        rt is T.UNKNOWN
        or e.type is T.UNKNOWN
        or e.type.name == rt.name
        or e.type is None
    ):
        return e
    if isinstance(e, Literal) and e.value is None:
        return Literal(None, rt)
    return SpecialForm(Form.CAST, [e], rt)


def _parse_date(text: str) -> int:
    y, m, d = (int(x) for x in text.strip().split("-"))
    return (datetime.date(y, m, d) - _EPOCH).days


_CMP_OPS = {"=", "<>", "!=", "<", "<=", ">", ">="}
_BOOL_OPS = {"and", "or"}
_ARITH_OPS = {"+", "-", "*", "/", "%"}


class ExprAnalyzer:
    """Analyzes one expression.  `hook(node)` may return an ir.Expr to
    short-circuit resolution (used for post-aggregation translation);
    `on_subquery(node)` handles subquery expressions by grafting plans
    (raises by default)."""

    def __init__(
        self,
        scope: Scope,
        hook: Optional[Callable] = None,
        on_subquery: Optional[Callable] = None,
        outer_refs: Optional[set] = None,
    ):
        self.scope = scope
        self.hook = hook
        self.on_subquery = on_subquery
        self.outer_refs = outer_refs  # set of symbol names resolved from parent

    def analyze(self, node: ast.Node) -> Expr:
        if self.hook is not None:
            out = self.hook(node, self)
            if out is not None:
                return out
        return self._analyze(node)

    # -- dispatch ------------------------------------------------------------

    def _analyze(self, node: ast.Node) -> Expr:
        m = getattr(self, "_a_" + type(node).__name__, None)
        if m is None:
            raise AnalysisError(f"unsupported expression: {type(node).__name__}")
        return m(node)

    def _a_Identifier(self, n: ast.Identifier) -> Expr:
        if len(n.parts) == 1:
            env = getattr(self, "_lambda_env", None)
            if env and n.parts[0] in env:
                return env[n.parts[0]]
        sym, outer = self.scope.resolve(n.parts)
        if outer and self.outer_refs is not None:
            self.outer_refs.add(sym.name)
        return sym.ref()

    def _analyze_lambda(self, lam: "ast.LambdaExpr", param_types) -> "ir.Lambda":
        """Bind lambda parameters and analyze the body (reference:
        ExpressionAnalyzer.visitLambdaExpression)."""
        from trino_tpu.expr.ir import Lambda, LambdaParam

        if len(lam.params) != len(param_types):
            raise AnalysisError(
                f"lambda expects {len(param_types)} parameters, "
                f"got {len(lam.params)}"
            )
        prev = getattr(self, "_lambda_env", None)
        env = dict(prev or {})
        for name, t in zip(lam.params, param_types):
            env[name] = LambdaParam(name, t)
        self._lambda_env = env
        try:
            body = self.analyze(lam.body)
        finally:
            self._lambda_env = prev
        return Lambda(list(lam.params), body, body.type)

    def _a_NumberLiteral(self, n: ast.NumberLiteral) -> Expr:
        t = n.text
        if "e" in t.lower():
            return Literal(float(t), T.DOUBLE)
        if "." in t:
            ip, fp = t.split(".")
            scale = len(fp)
            digits = len(ip.lstrip("-+").lstrip("0")) + scale
            if digits > 38:
                raise AnalysisError(
                    f"decimal literal exceeds precision 38: {t}"
                )
            # reference: DecimalParser sizes the literal's type by its
            # digits; 19+ digits become a long (two-limb) decimal
            return Literal(Decimal(t), T.DecimalType(max(digits, 1), scale))
        v = int(t)
        if n.decimal:
            # DECIMAL '123' is decimal(3,0), never integer/bigint — an
            # undotted 19+ digit literal must keep its long-decimal type
            digits = len(t.lstrip("-+").lstrip("0"))
            if digits > 38:
                raise AnalysisError(
                    f"decimal literal exceeds precision 38: {t}"
                )
            return Literal(Decimal(t), T.DecimalType(max(digits, 1), 0))
        if not -(2**63) <= v < 2**63:
            # an undotted literal beyond bigint range types as a decimal —
            # np.int64(v) in the compiler would otherwise crash with a raw
            # OverflowError, and cast contexts (including the recursive-CTE
            # working-table rebinding) legitimately produce these
            digits = len(t.lstrip("-+").lstrip("0"))
            if digits > 38:
                raise AnalysisError(
                    f"numeric literal exceeds precision 38: {t}"
                )
            return Literal(Decimal(t), T.DecimalType(max(digits, 1), 0))
        return Literal(v, T.INTEGER if -(2**31) <= v < 2**31 else T.BIGINT)

    def _a_StringLiteral(self, n: ast.StringLiteral) -> Expr:
        return Literal(n.value, T.VarcharType(len(n.value)))

    def _a_BooleanLiteral(self, n: ast.BooleanLiteral) -> Expr:
        return Literal(n.value, T.BOOLEAN)

    def _a_NullLiteral(self, n: ast.NullLiteral) -> Expr:
        return Literal(None, T.UNKNOWN)

    def _a_DateLiteral(self, n: ast.DateLiteral) -> Expr:
        return Literal(_parse_date(n.text), T.DATE)

    def _a_TimestampLiteral(self, n: ast.TimestampLiteral) -> Expr:
        import re as _re

        # normalize only an ISO 'T' separating date and time — a blanket
        # t->space replace would mangle zone names (UTC, America/Toronto)
        s = _re.sub(r"(?<=\d)[tT](?=\d)", " ", n.text.strip(), count=1)
        # trailing zone: '+05:30' / '-08:00' / ' UTC' / ' America/New_York'
        # (reference: SqlBase.g4 TIMESTAMP WITH TIME ZONE literal parsing)
        zone = None
        m = _re.search(r"\s*([+-]\d{2}:?\d{2})\s*$", s)
        if m:
            zone, s = m.group(1), s[: m.start()]
        else:
            m = _re.search(r"\s+([A-Za-z][A-Za-z_/+-]*(?:/[A-Za-z_]+)*)\s*$", s)
            if m:
                zone, s = m.group(1), s[: m.start()]
        if " " in s:
            d, tm = s.split(" ", 1)
        else:
            d, tm = s, "00:00:00"
        days = _parse_date(d)
        parts = tm.split(":")
        h = int(parts[0]) if parts and parts[0] else 0
        mi = int(parts[1]) if len(parts) > 1 else 0
        sec = float(parts[2]) if len(parts) > 2 else 0.0
        micros = days * 86_400_000_000 + (h * 3600 + mi * 60) * 1_000_000 + int(
            sec * 1_000_000
        )
        if zone is None:
            return Literal(micros, T.TIMESTAMP)
        local_millis = micros // 1000
        # resolve named zones at the local wall time (close enough for DST)
        off = T.zone_offset_minutes(zone, local_millis)
        utc_millis = local_millis - off * 60_000
        if zone[0] not in "+-" and zone.upper() not in ("UTC", "Z", "GMT"):
            off = T.zone_offset_minutes(zone, utc_millis)
            utc_millis = local_millis - off * 60_000
        return Literal(T.pack_tz(utc_millis, off), T.TIMESTAMP_TZ)

    def _a_TimeLiteral(self, n: ast.TimeLiteral) -> Expr:
        try:
            return Literal(T.parse_time_micros(n.text), T.TIME)
        except ValueError as e:
            raise AnalysisError(str(e))

    def _a_IntervalLiteral(self, n: ast.IntervalLiteral) -> Expr:
        # first-class interval value (reference: IntervalYearMonthType /
        # IntervalDayTimeType); date arithmetic still takes its inline
        # shortcut before this runs
        count = int(n.value) * n.sign
        u = n.unit.rstrip("s")
        if u in ("year", "month"):
            months = count * (12 if u == "year" else 1)
            return Literal(months, T.INTERVAL_YEAR_MONTH)
        mult = {
            "day": 86_400_000_000,
            "hour": 3_600_000_000,
            "minute": 60_000_000,
            "second": 1_000_000,
        }.get(u)
        if mult is None:
            raise AnalysisError(f"unsupported interval unit {n.unit}")
        return Literal(count * mult, T.INTERVAL_DAY)

    def _a_BinaryOp(self, n: ast.BinaryOp) -> Expr:
        op = n.op
        if op in _BOOL_OPS:
            l, r = self.analyze(n.left), self.analyze(n.right)
            return ir.and_(l, r) if op == "and" else ir.or_(l, r)
        if op in _CMP_OPS:
            l, r = self.analyze(n.left), self.analyze(n.right)
            l, r = self._coerce_temporal(l, r)
            self._check_comparable(l, r)
            return ir.comparison(op, l, r)
        if op == "||":
            l, r = self.analyze(n.left), self.analyze(n.right)
            if isinstance(l.type, T.ArrayType) or isinstance(r.type, T.ArrayType):
                if not (
                    isinstance(l.type, T.ArrayType)
                    and isinstance(r.type, T.ArrayType)
                ):
                    raise AnalysisError("|| requires two arrays or two strings")
                et = T.common_super_type(l.type.element, r.type.element)
                return Call("$array_concat", [l, r], T.ArrayType(et))
            return Call("concat", [l, r], T.VARCHAR)
        if op in _ARITH_OPS:
            # date +/- interval
            if (
                op in ("+", "-")
                and isinstance(n.right, ast.IntervalLiteral)
                and not isinstance(n.left, ast.IntervalLiteral)
            ):
                return self._date_interval(n.left, n.right, op)
            if (
                op == "+"
                and isinstance(n.left, ast.IntervalLiteral)
                and not isinstance(n.right, ast.IntervalLiteral)
            ):
                return self._date_interval(n.right, n.left, op)
            l, r = self.analyze(n.left), self.analyze(n.right)
            iv = self._interval_arith(op, l, r)
            if iv is not None:
                return iv
            rt = arith_result_type(op, l.type, r.type)
            name = {"+": "$add", "-": "$sub", "*": "$mul", "/": "$div", "%": "$mod"}[op]
            return Call(name, [l, r], rt)
        raise AnalysisError(f"unsupported operator {op}")

    def _interval_arith(self, op: str, l: Expr, r: Expr):
        """temporal +/- interval VALUE (column or expression operands;
        the literal-syntax shortcut in _a_BinaryOp handles the common
        `date + INTERVAL '1' DAY` spelling before analysis)."""
        if op not in ("+", "-"):
            return None
        temporal = (T.DATE, T.TIMESTAMP, T.TIMESTAMP_TZ)
        ilt = l.type in (T.INTERVAL_YEAR_MONTH, T.INTERVAL_DAY)
        irt = r.type in (T.INTERVAL_YEAR_MONTH, T.INTERVAL_DAY)
        if irt and l.type in temporal:
            base, delta = l, r
        elif ilt and r.type in temporal and op == "+":
            base, delta = r, l
        elif ilt and irt and l.type == r.type:
            # interval +/- interval of the same kind
            return Call(
                "$add" if op == "+" else "$sub", [l, r], l.type
            )
        else:
            return None
        if op == "-":
            delta = Call("$neg", [delta], delta.type)
        if delta.type is T.INTERVAL_YEAR_MONTH:
            return Call("date_add_months", [base, delta], base.type)
        # day-second interval: micros arithmetic
        if base.type is T.TIMESTAMP_TZ:
            # the packed (millis*4096 + offset) value needs unpack/repack
            return Call("$tz_add_micros", [base, delta], T.TIMESTAMP_TZ)
        if base.type is T.DATE:
            from trino_tpu.expr.constant_folding import try_fold

            folded = try_fold(delta)
            if isinstance(folded, Literal) and folded.value is not None:
                us = int(folded.value)
                if us % 86_400_000_000 != 0:
                    # reference: DateTimeOperators refuses sub-day interval
                    # components on a DATE
                    raise AnalysisError(
                        "cannot add an interval with a time component to a date"
                    )
                return Call(
                    "date_add_days",
                    [base, Literal(us // 86_400_000_000, T.BIGINT)],
                    T.DATE,
                )
            # non-constant interval: lift to timestamp (documented
            # divergence; the reference raises only on sub-day components)
            base = SpecialForm(Form.CAST, [base], T.TIMESTAMP)
        return Call("$add", [base, delta], base.type)

    def _date_interval(self, date_node, interval: ast.IntervalLiteral, op: str):
        d = self.analyze(date_node)
        count = int(interval.value) * interval.sign
        if op == "-":
            count = -count
        if interval.unit in ("day", "days"):
            return Call(
                "date_add_days", [d, Literal(count, T.BIGINT)], d.type
            )
        if interval.unit in ("month", "months"):
            return Call("date_add_months", [d, Literal(count, T.BIGINT)], d.type)
        if interval.unit in ("year", "years"):
            return Call("date_add_months", [d, Literal(count * 12, T.BIGINT)], d.type)
        if interval.unit in ("hour", "minute", "second") and d.type is T.TIMESTAMP:
            mult = {"hour": 3_600_000_000, "minute": 60_000_000, "second": 1_000_000}
            return Call(
                "$add",
                [d, Literal(count * mult[interval.unit], T.BIGINT)],
                T.TIMESTAMP,
            )
        raise AnalysisError(f"unsupported interval unit {interval.unit}")

    @staticmethod
    def _coerce_temporal(l: Expr, r: Expr):
        """`date_col = '2000-06-30'` style: a varchar literal compared with
        a DATE coerces to a date literal (reference: TypeCoercion's
        varchar->date implicit cast in comparisons)."""

        def lift(e: Expr, other_t):
            if (
                other_t is T.DATE
                and isinstance(e, Literal)
                and isinstance(e.value, str)
            ):
                try:
                    return Literal(_parse_date(e.value), T.DATE)
                except ValueError:
                    raise AnalysisError(f"invalid date literal: {e.value!r}")
            return e

        l, r = lift(l, r.type), lift(r, l.type)
        # timestamptz compares by UTC instant, not by packed (instant, zone)
        # bits (reference: TimestampWithTimeZoneOperators unpacks millis);
        # mixed tz/timestamp comparisons align both sides to instant micros
        tz_l = l.type is T.TIMESTAMP_TZ
        tz_r = r.type is T.TIMESTAMP_TZ
        if tz_l or tz_r:

            def instant(e: Expr) -> Expr:
                if e.type is T.TIMESTAMP_TZ:
                    return Call("$tz_instant", [e], T.TIMESTAMP)
                if e.type is T.DATE:
                    return Call(
                        "$mul",
                        [e, Literal(86_400_000_000, T.BIGINT)],
                        T.TIMESTAMP,
                    )
                return e

            l, r = instant(l), instant(r)
        return l, r

    def _check_comparable(self, l: Expr, r: Expr) -> None:
        lt, rt = l.type, r.type
        if lt == T.UNKNOWN or rt == T.UNKNOWN:
            return
        ls, rs = T.is_string_kind(lt), T.is_string_kind(rt)
        if ls != rs and not (lt is T.BOOLEAN and rt is T.BOOLEAN):
            if ls or rs:
                raise AnalysisError(f"cannot compare {lt.name} with {rt.name}")

    def _a_UnaryOp(self, n: ast.UnaryOp) -> Expr:
        if n.op == "not":
            return ir.not_(self.analyze(n.operand))
        if n.op == "-" and isinstance(n.operand, ast.NumberLiteral):
            # fold the sign into the literal text BEFORE range checks so
            # -9223372036854775808 (min bigint: unsigned text 2**63) types
            # (reference: Trino's min-long literal special case)
            return self._a_NumberLiteral(
                ast.NumberLiteral("-" + n.operand.text, n.operand.decimal)
            )
        v = self.analyze(n.operand)
        if n.op == "-":
            if isinstance(v, Literal) and v.value is not None:
                val = -v.value
                if T.is_integer_kind(v.type):
                    # negating a min-value literal overflows the type:
                    # wrap two's-complement like the device $neg would
                    # (np.int64(2**63) would crash the compiler)
                    import numpy as np

                    info = np.iinfo(v.type.np_dtype)
                    if not int(info.min) <= val <= int(info.max):
                        m = 1 << info.bits
                        val = ((val + (m >> 1)) % m) - (m >> 1)
                return Literal(val, v.type)
            return Call("$neg", [v], v.type)
        return v

    def _a_FunctionCall(self, n: ast.FunctionCall) -> Expr:
        if n.window is not None:
            raise AnalysisError("window functions not supported here")
        if n.name in AGG_FUNCS or (n.name == "count" and n.is_star):
            raise AnalysisError(
                f"aggregate function {n.name} not allowed in this context"
            )
        if n.within_group:
            raise AnalysisError(
                f"ORDER BY in arguments is not supported for {n.name}"
            )
        if n.name == "current_date":
            today = (datetime.date.today() - _EPOCH).days
            return Literal(today, T.DATE)
        if n.name == "current_user":
            from trino_tpu.runtime.session import CURRENT_USER

            u = CURRENT_USER.get()
            return Literal(u, T.VarcharType(len(u)))
        if n.name == "current_timestamp":
            # reference: scalar/CurrentTimestamp.java — session start instant
            # in the session zone (ours: UTC)
            import time as _time

            return Literal(
                T.pack_tz(int(_time.time() * 1000), 0), T.TIMESTAMP_TZ
            )
        if n.name == "localtimestamp":
            import time as _time

            return Literal(int(_time.time() * 1_000_000), T.TIMESTAMP)
        if n.name == "if":
            args = [self.analyze(a) for a in n.args]
            rt = T.common_super_type(
                args[1].type, args[2].type if len(args) > 2 else T.UNKNOWN
            )
            if len(args) == 2:
                args.append(Literal(None, rt))
            args[1] = _branch_cast(args[1], rt)
            args[2] = _branch_cast(args[2], rt)
            return SpecialForm(Form.IF, args, rt)
        if n.name == "coalesce":
            args = [self.analyze(a) for a in n.args]
            rt = T.UNKNOWN
            for a in args:
                rt = T.common_super_type(rt, a.type)
            args = [_branch_cast(a, rt) for a in args]
            return SpecialForm(Form.COALESCE, args, rt)
        if n.name == "nullif":
            args = [self.analyze(a) for a in n.args]
            return SpecialForm(Form.NULLIF, args, args[0].type)
        if n.name == "try":
            return SpecialForm(Form.TRY, [self.analyze(n.args[0])], T.UNKNOWN)
        if n.name == "concat_ws":
            # reference: ConcatWsFunction — NULL values are SKIPPED entirely
            # (no separator emitted for them, even in first position).
            # Rewritten into conditional pairwise concats with an "emitted
            # anything yet" boolean threaded through as an expression.
            if len(n.args) < 2:
                raise AnalysisError("concat_ws needs a separator and values")
            sep = self.analyze(n.args[0])
            parts = [self.analyze(a) for a in n.args[1:]]
            # Many non-literal string parts: the compiled IF/concat chain
            # would build cross-product dictionaries (doubling per part), so
            # route through the eager per-row host renderer instead (same
            # escape hatch as format()/array_join).
            if sum(1 for p in parts if not isinstance(p, Literal)) > 2:
                return Call("concat_ws", [sep] + parts, T.VARCHAR)
            empty = Literal("", T.VARCHAR)
            out: Expr = empty
            emitted: Expr = Literal(False, T.BOOLEAN)
            for pexp in parts:
                non_null = ir.not_(SpecialForm(Form.IS_NULL, [pexp], T.BOOLEAN))
                appended = SpecialForm(
                    Form.IF,
                    [
                        emitted,
                        Call("concat", [out, Call("concat", [sep, pexp], T.VARCHAR)], T.VARCHAR),
                        pexp,
                    ],
                    T.VARCHAR,
                )
                out = SpecialForm(Form.IF, [non_null, appended, out], T.VARCHAR)
                emitted = SpecialForm(Form.OR, [emitted, non_null], T.BOOLEAN)
            # NULL separator -> NULL result (reference: ConcatWsFunction)
            return SpecialForm(
                Form.IF,
                [
                    ir.not_(SpecialForm(Form.IS_NULL, [sep], T.BOOLEAN)),
                    out,
                    Literal(None, T.VARCHAR),
                ],
                T.VARCHAR,
            )
        if n.name in ("transform", "filter", "any_match", "all_match", "none_match"):
            # array lambda functions (reference: operator/scalar/
            # ArrayTransformFunction, ArrayFilterFunction, ArraysMatch*)
            if len(n.args) != 2:
                raise AnalysisError(f"{n.name} expects (array, lambda)")
            arr = self.analyze(n.args[0])
            if not isinstance(arr.type, T.ArrayType):
                raise AnalysisError(f"{n.name} expects an array argument")
            if not isinstance(n.args[1], ast.LambdaExpr):
                raise AnalysisError(f"{n.name} expects a lambda argument")
            lam = self._analyze_lambda(n.args[1], [arr.type.element])
            if n.name == "transform":
                rt: T.Type = T.ArrayType(lam.type)
            elif n.name == "filter":
                rt = arr.type
            else:
                rt = T.BOOLEAN
            return Call(n.name, [arr, lam], rt)
        if n.name == "zip_with":
            if len(n.args) != 3 or not isinstance(n.args[2], ast.LambdaExpr):
                raise AnalysisError("zip_with expects (array, array, lambda)")
            a1 = self.analyze(n.args[0])
            a2 = self.analyze(n.args[1])
            if not (
                isinstance(a1.type, T.ArrayType)
                and isinstance(a2.type, T.ArrayType)
            ):
                raise AnalysisError("zip_with expects two arrays")
            lam = self._analyze_lambda(
                n.args[2], [a1.type.element, a2.type.element]
            )
            return Call("zip_with", [a1, a2, lam], T.ArrayType(lam.type))
        if n.name == "reduce":
            # reduce(array, init, (s, x) -> comb, s -> final)
            if len(n.args) != 4 or not all(
                isinstance(a, ast.LambdaExpr) for a in n.args[2:]
            ):
                raise AnalysisError(
                    "reduce expects (array, init, (s, x) -> ..., s -> ...)"
                )
            arr = self.analyze(n.args[0])
            if not isinstance(arr.type, T.ArrayType):
                raise AnalysisError("reduce expects an array argument")
            init = self.analyze(n.args[1])
            comb = self._analyze_lambda(
                n.args[2], [init.type, arr.type.element]
            )
            final = self._analyze_lambda(n.args[3], [comb.type])
            return Call(n.name, [arr, init, comb, final], final.type)
        args = [self.analyze(a) for a in n.args]
        rt = scalar_result_type(n.name, [a.type for a in args])
        return Call(n.name, args, rt)

    def _a_CastExpr(self, n: ast.CastExpr) -> Expr:
        v = self.analyze(n.operand)
        to = T.parse_type(n.type_name)
        return SpecialForm(Form.CAST, [v], to)

    def _a_CaseExpr(self, n: ast.CaseExpr) -> Expr:
        args: list[Expr] = []
        rt = T.UNKNOWN
        for cond, val in n.whens:
            if n.operand is not None:
                c = ir.comparison(
                    "=", self.analyze(n.operand), self.analyze(cond)
                )
            else:
                c = self.analyze(cond)
            v = self.analyze(val)
            rt = T.common_super_type(rt, v.type)
            args.extend([c, v])
        if n.default is not None:
            d = self.analyze(n.default)
            rt = T.common_super_type(rt, d.type)
            args.append(d)
        # unify branch representations: widened branches get REAL casts
        # (a decimal branch next to a double branch must descale, not relabel)
        out = []
        for i, a in enumerate(args):
            is_value = (i % 2 == 1) or (i == len(args) - 1 and len(args) % 2 == 1)
            out.append(_branch_cast(a, rt) if is_value else a)
        return SpecialForm(Form.CASE, out, rt)

    def _a_InList(self, n: ast.InList) -> Expr:
        v = self.analyze(n.value)
        items = []
        for i in n.items:
            e = self.analyze(i)
            # the coercion may rewrite BOTH sides (e.g. timestamptz operands
            # align to instant micros) — the value rewrite must be kept, not
            # just the item one
            v, e = self._coerce_temporal(v, e)
            items.append(e)
        e = SpecialForm(Form.IN, [v] + items, T.BOOLEAN)
        return ir.not_(e) if n.negated else e

    def _a_Between(self, n: ast.Between) -> Expr:
        v = self.analyze(n.value)
        lo = self.analyze(n.low)
        hi = self.analyze(n.high)
        v, lo = self._coerce_temporal(v, lo)
        v, hi = self._coerce_temporal(v, hi)
        e = SpecialForm(Form.BETWEEN, [v, lo, hi], T.BOOLEAN)
        return ir.not_(e) if n.negated else e

    def _a_Like(self, n: ast.Like) -> Expr:
        args = [self.analyze(n.value), self.analyze(n.pattern)]
        if n.escape is not None:
            args.append(self.analyze(n.escape))
        e = Call("like", args, T.BOOLEAN)
        return ir.not_(e) if n.negated else e

    def _a_IsNull(self, n: ast.IsNull) -> Expr:
        e = SpecialForm(Form.IS_NULL, [self.analyze(n.value)], T.BOOLEAN)
        return ir.not_(e) if n.negated else e

    def _a_IsDistinctFrom(self, n: ast.IsDistinctFrom) -> Expr:
        l, r = self.analyze(n.left), self.analyze(n.right)
        eq = ir.comparison("=", l, r)
        ln = SpecialForm(Form.IS_NULL, [l], T.BOOLEAN)
        rn = SpecialForm(Form.IS_NULL, [r], T.BOOLEAN)
        both_null = ir.and_(ln, rn)
        neither_null_eq = ir.and_(ir.not_(ln), ir.not_(rn), eq)
        same = ir.or_(both_null, neither_null_eq)
        return same if n.negated else ir.not_(same)

    def _a_ArrayConstructor(self, n: ast.ArrayConstructor) -> Expr:
        items = [self.analyze(i) for i in n.items]
        et = T.UNKNOWN
        for i in items:
            et = T.common_super_type(et, i.type)
        if et == T.UNKNOWN:
            et = T.BIGINT
        return SpecialForm(Form.ARRAY, items, T.ArrayType(et))

    def _a_Subscript(self, n: ast.Subscript) -> Expr:
        base = self.analyze(n.base)
        idx = self.analyze(n.index)
        if isinstance(base.type, T.MapType):
            return SpecialForm(Form.SUBSCRIPT, [base, idx], base.type.value)
        if not isinstance(base.type, T.ArrayType):
            raise AnalysisError(
                f"subscript base must be an array, got {base.type.name}"
            )
        return SpecialForm(Form.SUBSCRIPT, [base, idx], base.type.element)

    def _a_Extract(self, n: ast.Extract) -> Expr:
        fn = {
            "year": "year", "month": "month", "day": "day",
            "quarter": "quarter", "week": "week",
            "dow": "day_of_week", "doy": "day_of_year",
            "hour": "hour", "minute": "minute", "second": "second",
            "timezone_hour": "timezone_hour",
            "timezone_minute": "timezone_minute",
        }.get(n.unit)
        if fn is None:
            raise AnalysisError(f"unsupported EXTRACT unit {n.unit}")
        return Call(fn, [self.analyze(n.operand)], T.BIGINT)

    # subquery expressions delegate to the planner's grafting callback

    def _a_ScalarSubquery(self, n: ast.ScalarSubquery) -> Expr:
        if self.on_subquery is None:
            raise AnalysisError("subquery not allowed in this context")
        return self.on_subquery(n, self)

    def _a_InSubquery(self, n: ast.InSubquery) -> Expr:
        if self.on_subquery is None:
            raise AnalysisError("subquery not allowed in this context")
        return self.on_subquery(n, self)

    def _a_Exists(self, n: ast.Exists) -> Expr:
        if self.on_subquery is None:
            raise AnalysisError("subquery not allowed in this context")
        return self.on_subquery(n, self)


def split_conjuncts(node: ast.Node) -> list[ast.Node]:
    if isinstance(node, ast.BinaryOp) and node.op == "and":
        return split_conjuncts(node.left) + split_conjuncts(node.right)
    return [node]


def collect_aggregates(node: ast.Node, out: list) -> None:
    """Find aggregate FunctionCalls, not descending into subqueries."""
    if isinstance(node, ast.FunctionCall) and node.window is None:
        from trino_tpu.planner.functions import REWRITTEN_AGGS

        if (
            node.name in AGG_FUNCS
            or node.name in REWRITTEN_AGGS
            or (node.is_star and node.name == "count")
        ):
            out.append(node)
            return  # nested aggs are invalid anyway
    for f in getattr(node, "__dataclass_fields__", {}):
        v = getattr(node, f)
        if isinstance(v, ast.Node):
            if isinstance(v, (ast.Query,)):
                continue
            collect_aggregates(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, ast.Node) and not isinstance(item, ast.Query):
                    collect_aggregates(item, out)
                elif isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, ast.Node) and not isinstance(
                            sub, ast.Query
                        ):
                            collect_aggregates(sub, out)
