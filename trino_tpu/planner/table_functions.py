"""Polymorphic table functions (ptf).

Reference roles: spi/function/table/ (ConnectorTableFunction, the TABLE(...)
invocation SPI) and operator/table/SequenceFunction.java,
ExcludeColumnsFunction.java — the two built-in ptfs the reference ships.

A table function receives its analyzed arguments and returns a logical plan
(RelationPlan), so invocation composes with the rest of the planner exactly
like a named relation: `SELECT * FROM TABLE(sequence(1, 1000))`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class TableFunction:
    name: str
    plan: Callable  # (planner, args: [ast.Node], outer, ctes) -> RelationPlan
    description: str = ""


TABLE_FUNCTIONS: dict = {}


def register_table_function(name: str, description: str = ""):
    def deco(fn):
        TABLE_FUNCTIONS[name] = TableFunction(name, fn, description)
        return fn

    return deco


@register_table_function(
    "sequence", "rows of sequential bigints: TABLE(sequence(start, stop[, step]))"
)
def _tf_sequence(planner, args, outer, ctes):
    """SequenceFunction.java:61 — start/stop/step literal rows.  Planned as
    UNNEST over the sequence array (rectangular device layout, one jitted
    expansion)."""
    from trino_tpu.planner.analyzer import AnalysisError
    from trino_tpu.sql import ast

    if not 2 <= len(args) <= 3:
        raise AnalysisError("sequence(start, stop[, step])")
    call = ast.FunctionCall("sequence", tuple(args))
    return planner.plan_unnest(
        ast.Unnest((call,), False),
        _single_row(planner),
        outer,
        ctes,
        alias=None,
        column_aliases=("sequential_number",),
        keep_left_fields=False,
    )


@register_table_function(
    "exclude_columns",
    "drop columns from a relation: TABLE(exclude_columns(TABLE(t), DESCRIPTOR(a, b)))",
)
def _tf_exclude_columns(planner, args, outer, ctes):
    """ExcludeColumnsFunction.java:71 — pass-through minus the descriptor's
    columns (planned as pruning projection)."""
    from trino_tpu.planner.analyzer import AnalysisError
    from trino_tpu.sql import ast

    if len(args) != 2 or not isinstance(args[0], ast.TableArgument):
        raise AnalysisError(
            "exclude_columns(TABLE(relation), DESCRIPTOR(col, ...))"
        )
    if not isinstance(args[1], ast.Descriptor):
        raise AnalysisError("second argument must be DESCRIPTOR(col, ...)")
    rp = planner.plan_relation(args[0].relation, outer, ctes)
    drop = {c.lower() for c in args[1].columns}
    missing = drop - {f.name for f in rp.fields}
    if missing:
        raise AnalysisError(
            f"descriptor columns not in relation: {sorted(missing)}"
        )
    kept = [f for f in rp.fields if f.name not in drop]
    if not kept:
        raise AnalysisError("exclude_columns would remove every column")
    from trino_tpu.planner import plan as P

    node = P.ProjectNode(rp.node, [(f.symbol, f.symbol.ref()) for f in kept])
    return _relation(planner, node, kept)


def _single_row(planner):
    from trino_tpu.planner import plan as P
    from trino_tpu.planner.logical_planner import RelationPlan

    return RelationPlan(P.ValuesNode([], [()]), [])


def _relation(planner, node, fields):
    from trino_tpu.planner.logical_planner import RelationPlan

    return RelationPlan(node, list(fields))
