"""Column pruning: drop unreferenced symbols from every node top-down.

Reference role: iterative/rule/PruneUnreferencedOutputs / Prune*Columns rule
family.  Matters doubly on TPU: narrower scans shrink the host->device feed
(HBM bandwidth is the bottleneck, SURVEY.md §7) and narrower join inputs
shrink the gather expansion the sort-based join performs per output row.
"""

from __future__ import annotations

from trino_tpu.expr.ir import Expr
from trino_tpu.planner import plan as P
from trino_tpu.planner.join_planning import collect_symbol_names


def _refs(*exprs) -> set:
    acc: set = set()
    for e in exprs:
        if isinstance(e, Expr):
            collect_symbol_names(e, acc)
    return acc


def prune(node: P.PlanNode) -> P.PlanNode:
    if isinstance(node, P.OutputNode):
        return P.OutputNode(
            _prune(node.source, {s.name for s in node.symbols}),
            node.column_names,
            node.symbols,
        )
    return _prune(node, {s.name for s in node.outputs})


def _keep(symbols, needed: set) -> list:
    kept = [s for s in symbols if s.name in needed]
    return kept


def _prune(node: P.PlanNode, needed: set) -> P.PlanNode:
    if isinstance(node, P.TableScanNode):
        pred_refs = _refs(node.pushed_predicate)
        assigns = [
            (s, c) for s, c in node.assignments if s.name in needed | pred_refs
        ]
        if not assigns:  # keep one column for row counting
            assigns = node.assignments[:1]
        return P.TableScanNode(node.handle, node.table_meta, assigns, node.pushed_predicate)

    if isinstance(node, P.FilterNode):
        child = _prune(node.source, needed | _refs(node.predicate))
        return P.FilterNode(child, node.predicate)

    if isinstance(node, P.SampleNode):
        # sampling reads no symbols: pass the needed set straight through
        return P.SampleNode(_prune(node.source, needed), node.ratio)

    if isinstance(node, P.ProjectNode):
        assigns = [(s, e) for s, e in node.assignments if s.name in needed]
        if not assigns:
            assigns = node.assignments[:1]
        child = _prune(node.source, _refs(*(e for _, e in assigns)))
        return P.ProjectNode(child, assigns)

    if isinstance(node, P.AggregationNode):
        aggs = [(s, a) for s, a in node.aggregations if s.name in needed]
        child_needed = {s.name for s in node.group_symbols}
        for _, a in aggs:
            child_needed |= _refs(*a.args, a.filter)
        return P.AggregationNode(
            _prune(node.source, child_needed), node.group_symbols, aggs, node.step
        )

    if isinstance(node, P.JoinNode):
        crit_l = {l.name for l, _ in node.criteria}
        crit_r = {r.name for _, r in node.criteria}
        filt = _refs(node.filter)
        lnames = {s.name for s in node.left.outputs}
        rnames = {s.name for s in node.right.outputs}
        left = _prune(node.left, (needed | filt | crit_l) & lnames)
        right = _prune(node.right, (needed | filt | crit_r) & rnames)
        return P.JoinNode(
            node.kind, left, right, node.criteria, node.filter, node.distribution
        )

    if isinstance(node, P.SemiJoinNode):
        filt = _refs(node.filter)
        snames = {s.name for s in node.source.outputs}
        fnames = {s.name for s in node.filtering.outputs}
        source = _prune(
            node.source, ((needed | filt) & snames) | {node.source_key.name}
        )
        filtering = _prune(
            node.filtering, (filt & fnames) | {node.filtering_key.name}
        )
        return P.SemiJoinNode(
            source, filtering, node.source_key, node.filtering_key, node.mark,
            node.filter, node.null_aware,
        )

    if isinstance(node, P.WindowNode):
        fns = [(s, f) for s, f in node.functions if s.name in needed]
        child_needed = set(needed) & {s.name for s in node.source.outputs}
        child_needed |= {s.name for s in node.partition_by}
        child_needed |= {s.name for s, _, _ in node.order_by}
        for _, f in fns:
            child_needed |= _refs(*f.args, f.default)
        return P.WindowNode(
            _prune(node.source, child_needed), node.partition_by,
            node.order_by, fns,
        )

    if isinstance(node, (P.SortNode, P.TopNNode)):
        child_needed = needed | {s.name for s, _, _ in node.orderings}
        child = _prune(node.source, child_needed)
        if isinstance(node, P.SortNode):
            return P.SortNode(child, node.orderings)
        return P.TopNNode(child, node.orderings, node.count)

    if isinstance(node, P.UnionNode):
        idx = [i for i, s in enumerate(node.symbols) if s.name in needed]
        if not idx:
            idx = [0]
        symbols = [node.symbols[i] for i in idx]
        sources, source_symbols = [], []
        for child, mapping in zip(node.sources, node.source_symbols):
            kept = [mapping[i] for i in idx]
            sources.append(_prune(child, {m.name for m in kept}))
            source_symbols.append(kept)
        return P.UnionNode(sources, symbols, source_symbols)

    if isinstance(node, P.ExchangeNode):
        child_needed = needed | {s.name for s in node.partition_symbols}
        child_needed |= {s.name for s, _, _ in node.orderings}
        return P.ExchangeNode(
            _prune(node.source, child_needed), node.kind,
            node.partition_symbols, node.orderings,
        )

    if isinstance(node, (P.LimitNode, P.EnforceSingleRowNode)):
        child = _prune(node.children[0], needed)
        return node.with_children([child])

    if isinstance(node, P.ValuesNode):
        return node

    # default: require everything from children
    kids = [
        _prune(c, needed | {s.name for s in c.outputs}) for c in node.children
    ]
    return node.with_children(kids) if kids else node
