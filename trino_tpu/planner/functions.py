"""Function resolution + result-type rules for the analyzer.

Reference roles: metadata/GlobalFunctionCatalog + FunctionManager (function
binding) and type/TypeCoercion (operator result types).  Decimal arithmetic
follows the reference's short-decimal rules with precision capped at 18
(device i64); decimal division results are DOUBLE (documented divergence —
ratio outputs are tolerance-compared like QueryAssertions does).
"""

from __future__ import annotations

from trino_tpu import types as T

#: SQL aggregate functions -> AggSpec name
AGG_FUNCS = {
    "count": "count",
    "sum": "sum",
    "avg": "avg",
    "min": "min",
    "max": "max",
    "any_value": "any_value",
    "arbitrary": "any_value",
    "bool_and": "bool_and",
    "bool_or": "bool_or",
    "every": "bool_and",
    # moment family (reference: operator/aggregation/ Variance/StdDev states)
    "stddev": "stddev_samp",
    "stddev_samp": "stddev_samp",
    "stddev_pop": "stddev_pop",
    "variance": "var_samp",
    "var_samp": "var_samp",
    "var_pop": "var_pop",
    # approx_percentile computes the exact percentile (sort-based engines get
    # exactness cheaper than a qdigest; "approximate" permits exact answers)
    "approx_percentile": "percentile",
    # exact distinct count satisfies the approx contract (agg_symbol rewrites
    # this to a DISTINCT count before planning)
    "approx_distinct": "approx_distinct",
    # holistic aggregates (whole group materialized on one node; reference:
    # operator/aggregation/ArrayAggregationFunction, MapAggAggregationFunction)
    "array_agg": "array_agg",
    "map_agg": "map_agg",
    "listagg": "listagg",
    "string_agg": "listagg",
    # bivariate regression family (reference: operator/aggregation/
    # CovarianceAggregation, CorrelationAggregation, RegrAggregation)
    "covar_samp": "covar_samp",
    "covar_pop": "covar_pop",
    "corr": "corr",
    "regr_slope": "regr_slope",
    "regr_intercept": "regr_intercept",
    # order-independent multiset checksum (reference: ChecksumAggregation)
    "checksum": "checksum",
    # value-at-extremum pair aggregates (reference: operator/aggregation/
    # MinMaxByNAggregation family, the N=1 forms); distributed via the
    # group-key repartition path (joint key/value state does not merge
    # column-independently)
    "min_by": "min_by",
    "max_by": "max_by",
}

#: composite aggregates planned as rewrites over simpler ones (the
#: geometric_mean -> exp(avg(ln(x))) family); consulted by BOTH aggregate
#: detection (analyzer.collect_aggregates) and the planning hook
REWRITTEN_AGGS = ("geometric_mean", "count_if")

#: aggregates that need every group row co-located (no partial/merge states)
HOLISTIC_AGGS = ("percentile", "array_agg", "map_agg", "listagg", "min_by", "max_by")

#: the holistic subset that still DISTRIBUTES: after a hash repartition on
#: the group keys each group is whole on one worker, and the single-stage
#: kernel runs fully inside the SPMD step (no eager host work)
PARTITIONABLE_HOLISTIC = ("percentile", "min_by", "max_by")

#: aggregates whose grouped state is the (count, sum, sum-of-squares) triple
MOMENT_AGGS = ("stddev_samp", "stddev_pop", "var_samp", "var_pop")


def agg_result_type(name: str, arg_type: T.Type | None, arg_type2: T.Type | None = None) -> T.Type:
    if name in ("count", "count_star", "approx_distinct", "checksum"):
        return T.BIGINT
    if name == "sum":
        if arg_type is None:
            raise TypeError("sum requires an argument")
        if isinstance(arg_type, T.DecimalType):
            # reference: DecimalSumAggregation widens to decimal(38, s) with
            # an Int128 state; the two-limb exact sum lives in types/int128
            return T.DecimalType(38, arg_type.scale)
        if arg_type.name in ("double", "real"):
            return T.DOUBLE
        return T.BIGINT
    if name == "avg":
        if isinstance(arg_type, T.DecimalType):
            return arg_type
        return T.DOUBLE
    if name in ("min", "max", "any_value"):
        return arg_type
    if name in ("bool_and", "bool_or"):
        return T.BOOLEAN
    if name in MOMENT_AGGS:
        return T.DOUBLE
    if name in ("percentile", "approx_percentile"):
        return arg_type
    if name == "array_agg":
        return T.ArrayType(arg_type)
    if name == "listagg":
        return T.VARCHAR
    if name in ("covar_samp", "covar_pop", "corr", "regr_slope", "regr_intercept"):
        return T.DOUBLE
    if name in ("min_by", "max_by"):
        if arg_type2 is None:
            raise TypeError(f"{name} requires 2 arguments (value, key)")
        return arg_type
    if name == "map_agg":
        return T.MapType(arg_type, arg_type2 if arg_type2 is not None else T.BIGINT)
    raise TypeError(f"unknown aggregate {name}")


def arith_result_type(op: str, a: T.Type, b: T.Type) -> T.Type:
    da, db = isinstance(a, T.DecimalType), isinstance(b, T.DecimalType)
    if a.name in ("double", "real") or b.name in ("double", "real"):
        return T.DOUBLE
    if op in ("+", "-"):
        if da or db:
            # reference rule: p = max(p1-s1, p2-s2) + max(s1, s2) + 1, cap 38
            sa = a.scale if da else 0
            sb = b.scale if db else 0
            ia = (a.precision - sa) if da else T.INT_DIGITS.get(a.name, 19)
            ib = (b.precision - sb) if db else T.INT_DIGITS.get(b.name, 19)
            s = max(sa, sb)
            return T.DecimalType(min(max(ia, ib) + s + 1, 38), s)
        if a is T.DATE or b is T.DATE:
            return T.DATE  # date +/- interval-day
        return T.common_super_type(a, b)
    if op == "*":
        if da or db:
            # reference rule: p = p1 + p2, cap 38 (DecimalOperators.multiply)
            sa = a.scale if da else 0
            sb = b.scale if db else 0
            pa = a.precision if da else T.INT_DIGITS.get(a.name, 19)
            pb = b.precision if db else T.INT_DIGITS.get(b.name, 19)
            return T.DecimalType(min(pa + pb, 38), sa + sb)
        return T.common_super_type(a, b)
    if op == "/":
        if da or db:
            return T.DOUBLE  # divergence: reference returns decimal
        if T.is_integer_kind(a) and T.is_integer_kind(b):
            return T.common_super_type(a, b)
        return T.DOUBLE
    if op == "%":
        return T.common_super_type(a, b)
    raise TypeError(f"cannot apply {op} to {a.name}, {b.name}")


#: scalar function result types: name -> fn(arg_types) -> Type
def _fixed(t):
    return lambda args: t


def _same_as_first(args):
    return args[0]


SCALAR_RESULT = {
    "year": _fixed(T.BIGINT),
    "month": _fixed(T.BIGINT),
    "day": _fixed(T.BIGINT),
    "day_of_month": _fixed(T.BIGINT),
    "quarter": _fixed(T.BIGINT),
    "week": _fixed(T.BIGINT),
    "day_of_week": _fixed(T.BIGINT),
    "dow": _fixed(T.BIGINT),
    "day_of_year": _fixed(T.BIGINT),
    "doy": _fixed(T.BIGINT),
    "date_add_days": _same_as_first,
    "date_add_months": _same_as_first,
    "date_trunc_month": _fixed(T.DATE),
    "date_trunc_year": _fixed(T.DATE),
    "date_trunc": lambda args: args[1],
    "date_add": lambda args: args[2],
    "date_diff": _fixed(T.BIGINT),
    "substr": _fixed(T.VARCHAR),
    "substring": _fixed(T.VARCHAR),
    "upper": _fixed(T.VARCHAR),
    "lower": _fixed(T.VARCHAR),
    "trim": _fixed(T.VARCHAR),
    "ltrim": _fixed(T.VARCHAR),
    "rtrim": _fixed(T.VARCHAR),
    "reverse": _fixed(T.VARCHAR),
    "replace": _fixed(T.VARCHAR),
    "concat": _fixed(T.VARCHAR),
    "length": _fixed(T.BIGINT),
    "strpos": _fixed(T.BIGINT),
    "position": _fixed(T.BIGINT),
    "starts_with": _fixed(T.BOOLEAN),
    "like": _fixed(T.BOOLEAN),
    "regexp_like": _fixed(T.BOOLEAN),
    "regexp_extract": _fixed(T.VARCHAR),
    "regexp_replace": _fixed(T.VARCHAR),
    "abs": _same_as_first,
    "sign": _same_as_first,
    "sqrt": _fixed(T.DOUBLE),
    "cbrt": _fixed(T.DOUBLE),
    "exp": _fixed(T.DOUBLE),
    "ln": _fixed(T.DOUBLE),
    "log10": _fixed(T.DOUBLE),
    "log2": _fixed(T.DOUBLE),
    "sin": _fixed(T.DOUBLE),
    "cos": _fixed(T.DOUBLE),
    "tan": _fixed(T.DOUBLE),
    "degrees": _fixed(T.DOUBLE),
    "radians": _fixed(T.DOUBLE),
    "power": _fixed(T.DOUBLE),
    "pow": _fixed(T.DOUBLE),
    "mod": _same_as_first,
    # reference: floor/ceil(decimal(p,s)) -> decimal(p - s + min(s,1), 0)
    "floor": lambda args: T.DecimalType(
        max(args[0].precision - args[0].scale + min(args[0].scale, 1), 1), 0
    )
    if isinstance(args[0], T.DecimalType)
    else args[0],
    "ceil": lambda args: T.DecimalType(
        max(args[0].precision - args[0].scale + min(args[0].scale, 1), 1), 0
    )
    if isinstance(args[0], T.DecimalType)
    else args[0],
    "ceiling": lambda args: T.DecimalType(
        max(args[0].precision - args[0].scale + min(args[0].scale, 1), 1), 0
    )
    if isinstance(args[0], T.DecimalType)
    else args[0],
    "round": lambda args: args[0],
    "greatest": _same_as_first,
    "least": _same_as_first,
    # -- row-pattern navigation (valid only inside MATCH_RECOGNIZE DEFINE;
    # the pattern operator rewrites them to $nav_prev/$nav_next) -----------
    "prev": _same_as_first,
    "next": _same_as_first,
    # -- string breadth (reference: scalar/StringFunctions, UrlFunctions) ---
    "split_part": _fixed(T.VARCHAR),
    "lpad": _fixed(T.VARCHAR),
    "rpad": _fixed(T.VARCHAR),
    "translate": _fixed(T.VARCHAR),
    "codepoint": _fixed(T.BIGINT),
    "chr": _fixed(T.VARCHAR),
    "normalize": _fixed(T.VARCHAR),
    "levenshtein_distance": _fixed(T.BIGINT),
    "url_extract_host": _fixed(T.VARCHAR),
    "url_extract_protocol": _fixed(T.VARCHAR),
    "url_extract_path": _fixed(T.VARCHAR),
    "url_extract_query": _fixed(T.VARCHAR),
    "url_extract_fragment": _fixed(T.VARCHAR),
    "url_extract_port": _fixed(T.BIGINT),
    "url_encode": _fixed(T.VARCHAR),
    "url_decode": _fixed(T.VARCHAR),
    # -- math breadth (reference: scalar/MathFunctions) ---------------------
    "asin": _fixed(T.DOUBLE),
    "acos": _fixed(T.DOUBLE),
    "atan": _fixed(T.DOUBLE),
    "atan2": _fixed(T.DOUBLE),
    "sinh": _fixed(T.DOUBLE),
    "cosh": _fixed(T.DOUBLE),
    "tanh": _fixed(T.DOUBLE),
    "log": _fixed(T.DOUBLE),
    "truncate": _fixed(T.DOUBLE),
    "e": _fixed(T.DOUBLE),
    "pi": _fixed(T.DOUBLE),
    "nan": _fixed(T.DOUBLE),
    "infinity": _fixed(T.DOUBLE),
    "is_nan": _fixed(T.BOOLEAN),
    "is_finite": _fixed(T.BOOLEAN),
    "is_infinite": _fixed(T.BOOLEAN),
    "width_bucket": _fixed(T.BIGINT),
    # -- bitwise (reference: scalar/BitwiseFunctions) -----------------------
    "bitwise_and": _fixed(T.BIGINT),
    "bitwise_or": _fixed(T.BIGINT),
    "bitwise_xor": _fixed(T.BIGINT),
    "bitwise_not": _fixed(T.BIGINT),
    "bitwise_left_shift": _fixed(T.BIGINT),
    "bitwise_right_shift_arithmetic": _fixed(T.BIGINT),
    "bit_count": _fixed(T.BIGINT),
    # -- arrays (reference: operator/scalar/Array*Function.java) ------------
    "hour": _fixed(T.BIGINT),
    "minute": _fixed(T.BIGINT),
    "second": _fixed(T.BIGINT),
    "millisecond": _fixed(T.BIGINT),
    "timezone_hour": _fixed(T.BIGINT),
    "timezone_minute": _fixed(T.BIGINT),
    "at_timezone": _fixed(T.TIMESTAMP_TZ),
    "with_timezone": _fixed(T.TIMESTAMP_TZ),
    "from_unixtime": lambda args: T.TIMESTAMP
    if len(args) == 1
    else T.TIMESTAMP_TZ,
    "to_unixtime": _fixed(T.DOUBLE),
    "cardinality": _fixed(T.BIGINT),
    "element_at": lambda args: args[0].element
    if isinstance(args[0], T.ArrayType)
    else args[0].value
    if isinstance(args[0], T.MapType)
    else T.UNKNOWN,
    # -- maps (reference: operator/scalar/MapConstructor.java etc) ----------
    "map": lambda args: T.MapType(
        args[0].element if isinstance(args[0], T.ArrayType) else T.BIGINT,
        args[1].element if isinstance(args[1], T.ArrayType) else T.BIGINT,
    ),
    "map_keys": lambda args: T.ArrayType(
        args[0].key if isinstance(args[0], T.MapType) else T.BIGINT
    ),
    "map_values": lambda args: T.ArrayType(
        args[0].value if isinstance(args[0], T.MapType) else T.BIGINT
    ),
    "map_concat": _same_as_first,
    "$array_concat": _same_as_first,
    "slice": _same_as_first,
    "arrays_overlap": _fixed(T.BOOLEAN),
    "array_intersect": _same_as_first,
    "array_except": _same_as_first,
    "array_union": _same_as_first,
    "zip_with": _same_as_first,  # analyzer overrides with the lambda's type
    "transform": _same_as_first,  # analyzer overrides with real typing
    "filter": _same_as_first,
    "any_match": _fixed(T.BOOLEAN),
    "all_match": _fixed(T.BOOLEAN),
    "none_match": _fixed(T.BOOLEAN),
    "reduce": _same_as_first,
    "typeof": _fixed(T.VARCHAR),
    "version": _fixed(T.VARCHAR),
    "concat_ws": _fixed(T.VARCHAR),
    "contains": _fixed(T.BOOLEAN),
    "array_position": _fixed(T.BIGINT),
    "array_join": _fixed(T.VARCHAR),
    "format": _fixed(T.VARCHAR),
    "array_max": lambda args: args[0].element
    if isinstance(args[0], T.ArrayType)
    else args[0],
    "array_min": lambda args: args[0].element
    if isinstance(args[0], T.ArrayType)
    else args[0],
    "array_sort": _same_as_first,
    "array_distinct": _same_as_first,
    "sequence": _fixed(T.ArrayType(T.BIGINT)),
    "repeat": lambda args: T.ArrayType(args[0]),
    "split": _fixed(T.ArrayType(T.VARCHAR)),
    # -- json (reference: operator/scalar/json/JsonExtract.java) ------------
    "json_extract_scalar": _fixed(T.VARCHAR),
    "json_extract": _fixed(T.VARCHAR),
    "json_array_length": _fixed(T.BIGINT),
    "json_size": _fixed(T.BIGINT),
    "json_parse": _fixed(T.VARCHAR),
    "json_format": _fixed(T.VARCHAR),
}


def scalar_result_type(name: str, arg_types) -> T.Type:
    fn = SCALAR_RESULT.get(name)
    if fn is None:
        raise TypeError(f"unknown function: {name}")
    return fn(list(arg_types))
