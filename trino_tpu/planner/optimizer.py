"""Logical plan optimizer (reference: sql/planner/PlanOptimizers.java:267 and
the iterative rules under sql/planner/iterative/rule/).

Round-1 scope: a bottom-up rewrite driver with the rules that matter most for
the TPU execution model — constant folding, filter merging/pushdown into
scans, and identity-projection removal.  Cost-based join ordering and
distribution selection land with the distributed planner.
"""

from __future__ import annotations

from trino_tpu.expr.constant_folding import try_fold as fold
from trino_tpu.expr.ir import and_
from trino_tpu.planner import plan as P


#: rule-fire counters of the LAST optimize() call (reference: the
#: IterativeOptimizer rule stats surfaced by EXPLAIN) — read by EXPLAIN
LAST_RULE_STATS: dict = {}


def _rule_name(rule) -> str:
    n = getattr(rule, "__name__", None)
    return n if n and n != "<lambda>" else "eliminate_cross_joins"


def _rewrite_bottom_up(node: P.PlanNode, rules, stats=None) -> P.PlanNode:
    kids = node.children
    if kids:
        node = node.with_children(
            [_rewrite_bottom_up(c, rules, stats) for c in kids]
        )
    changed = True
    while changed:
        changed = False
        for rule in rules:
            out = rule(node)
            if out is not None:
                node = out
                changed = True
                if stats is not None:
                    name = _rule_name(rule)
                    stats[name] = stats.get(name, 0) + 1
    return node


def rule_fold_constants(node: P.PlanNode):
    """Constant-fold expressions in filters/projections (reference:
    iterative/rule/SimplifyExpressions.java)."""
    if isinstance(node, P.FilterNode):
        folded = fold(node.predicate)
        if folded is not node.predicate and folded != node.predicate:
            return P.FilterNode(node.source, folded)
    if isinstance(node, P.ProjectNode):
        out = [(s, fold(e)) for s, e in node.assignments]
        if any(a is not b for (_, a), (_, b) in zip(out, node.assignments)):
            if [e.key() for _, e in out] != [e.key() for _, e in node.assignments]:
                return P.ProjectNode(node.source, out)
    return None


def rule_merge_filters(node: P.PlanNode):
    """Filter(Filter(x)) -> Filter(x) with AND (reference:
    iterative/rule/MergeFilters.java)."""
    if isinstance(node, P.FilterNode) and isinstance(node.source, P.FilterNode):
        return P.FilterNode(
            node.source.source, and_(node.source.predicate, node.predicate)
        )
    return None


def rule_push_filter_into_scan(node: P.PlanNode):
    """Filter(TableScan) -> TableScan with pushed predicate (reference:
    iterative/rule/PushPredicateIntoTableScan.java).  The scan operator fuses
    the predicate into its first device step, so filtering happens in the
    same XLA program as the host->device feed."""
    if isinstance(node, P.FilterNode) and isinstance(node.source, P.TableScanNode):
        scan = node.source
        pred = (
            node.predicate
            if scan.pushed_predicate is None
            else and_(scan.pushed_predicate, node.predicate)
        )
        return P.TableScanNode(
            scan.handle, scan.table_meta, scan.assignments, pred
        )
    return None


_MD_COUNTER = __import__("itertools").count()


def rule_mixed_distinct(node: P.PlanNode):
    """Rewrite mixed/multi-argument DISTINCT aggregates into MarkDistinct +
    FILTERed plain aggregates (reference: plan/MarkDistinctNode.java and
    the MultipleDistinctAggregationToMarkDistinct rule)."""
    from trino_tpu import types as T
    from trino_tpu.expr.ir import SymbolRef

    if not isinstance(node, P.AggregationNode) or node.step != "single":
        return None
    distincts = [(s, a) for s, a in node.aggregations if a.distinct]
    if not distincts:
        return None
    arg_sets = {tuple(x.key() for x in a.args) for _, a in distincts}
    if len(arg_sets) == 1 and all(a.distinct for _, a in node.aggregations):
        return None  # uniform shape: the execution-level pre-agg handles it
    if any(a.filter is not None for _, a in distincts):
        return None  # DISTINCT + FILTER: unsupported downstream
    if any(
        not all(isinstance(x, SymbolRef) for x in a.args) for _, a in distincts
    ):
        return None
    src = node.source
    marks: dict = {}
    new_aggs = []
    for s, a in node.aggregations:
        if not a.distinct:
            new_aggs.append((s, a))
            continue
        k = tuple(x.key() for x in a.args)
        if k not in marks:
            mark = P.Symbol(f"$distinct_{next(_MD_COUNTER)}", T.BOOLEAN)
            keys = list(node.group_symbols) + [
                P.Symbol(x.name, x.type) for x in a.args
            ]
            src = P.MarkDistinctNode(src, keys, mark)
            marks[k] = mark
        new_aggs.append(
            (s, P.Aggregation(a.function, a.args, False, marks[k].ref()))
        )
    return P.AggregationNode(src, node.group_symbols, new_aggs, node.step)


def rule_push_filter_through_project(node: P.PlanNode):
    """Filter(Project) -> Project(Filter) with the project's assignments
    inlined into the predicate (reference: PredicatePushDown's
    ExpressionSymbolInliner).  Safe because every engine expression is
    deterministic; XLA CSE dedupes the doubled computation inside the fused
    fragment."""
    from trino_tpu.expr.ir import substitute_symbols

    if not (
        isinstance(node, P.FilterNode) and isinstance(node.source, P.ProjectNode)
    ):
        return None
    proj = node.source
    mapping = {s.name: e for s, e in proj.assignments}
    return P.ProjectNode(
        P.FilterNode(proj.source, substitute_symbols(node.predicate, mapping)),
        proj.assignments,
    )


def rule_push_filter_through_sample(node: P.PlanNode):
    """Filter(Sample) -> Sample(Filter): Bernoulli keep/drop is independent
    per row, so filtering first is equivalent and lets predicates reach the
    scan (reference: PredicatePushDown's SampleNode pass-through)."""
    if not (
        isinstance(node, P.FilterNode) and isinstance(node.source, P.SampleNode)
    ):
        return None
    sample = node.source
    return P.SampleNode(
        P.FilterNode(sample.source, node.predicate), sample.ratio
    )


def rule_push_filter_through_union(node: P.PlanNode):
    """Filter(Union) -> Union(Filter(child_i)) with the predicate rewritten
    per branch through the union's symbol mapping (reference:
    iterative/rule/PushdownFilterIntoUnion semantics via PredicatePushDown's
    union handling).  Filtering before the concat shrinks every branch's
    static shapes and exchanges."""
    from trino_tpu import types as T
    from trino_tpu.expr.ir import Form, SpecialForm, substitute_symbols

    if not (isinstance(node, P.FilterNode) and isinstance(node.source, P.UnionNode)):
        return None
    u = node.source
    if not u.source_symbols:
        return None
    new_sources = []
    for i, src in enumerate(u.sources):
        mapping = {}
        for j, out in enumerate(u.symbols):
            s = u.source_symbols[i][j]
            e = s.ref()
            if s.type.name != out.type.name:
                if s.type is T.UNKNOWN or out.type is T.UNKNOWN:
                    return None  # NULL-literal branch: let the union coerce
                # the branch column COERCES to the union output type (date
                # unioned with timestamp compares in micros, not days) —
                # push the same cast the union lowering inserts
                e = SpecialForm(Form.CAST, [e], out.type)
            mapping[out.name] = e
        new_sources.append(
            P.FilterNode(src, substitute_symbols(node.predicate, mapping))
        )
    return P.UnionNode(new_sources, u.symbols, u.source_symbols)


def rule_push_filter_through_aggregation(node: P.PlanNode):
    """Conjuncts over GROUP KEYS move below the aggregation (reference:
    iterative/rule/PushPredicateThroughProjectIntoRowNumber family /
    PredicatePushDown's aggregation handling) — pre-agg filtering shrinks
    the grouped sort and every aggregate's input."""
    from trino_tpu.expr.ir import and_
    from trino_tpu.planner.join_planning import (
        collect_symbol_names,
        split_conjuncts_ir,
    )

    if not (
        isinstance(node, P.FilterNode)
        and isinstance(node.source, P.AggregationNode)
    ):
        return None
    agg = node.source
    if not agg.group_symbols or agg.step != "single":
        return None
    group_names = {s.name for s in agg.group_symbols}
    below, above = [], []
    for c in split_conjuncts_ir(node.predicate):
        if collect_symbol_names(c) <= group_names:
            below.append(c)
        else:
            above.append(c)
    if not below:
        return None
    new_agg = P.AggregationNode(
        P.FilterNode(agg.source, and_(*below)),
        agg.group_symbols,
        agg.aggregations,
        agg.step,
    )
    if above:
        return P.FilterNode(new_agg, and_(*above))
    return new_agg


def rule_remove_identity_project(node: P.PlanNode):
    """Drop no-op projections (reference: iterative/rule/
    RemoveRedundantIdentityProjections.java)."""
    if isinstance(node, P.ProjectNode) and node.is_identity():
        src = node.source.outputs
        if [s.name for s in src] == [s.name for s, _ in node.assignments]:
            return node.source
    return None


def rule_remove_trivial_filter(node: P.PlanNode):
    """Filter(TRUE) -> source; Filter(FALSE/NULL) -> empty Values
    (reference: iterative/rule/RemoveTrivialFilters.java)."""
    from trino_tpu.expr.ir import Literal

    if not isinstance(node, P.FilterNode):
        return None
    p = node.predicate
    if isinstance(p, Literal):
        if p.value is True:
            return node.source
        if p.value in (False, None):
            return P.ValuesNode(list(node.source.outputs), [])
    return None


def rule_merge_limits(node: P.PlanNode):
    """Limit(a, Limit(b, x)) -> Limit(min(a,b), x) (reference:
    iterative/rule/MergeLimits.java); only when neither carries OFFSET."""
    if not (
        isinstance(node, P.LimitNode)
        and isinstance(node.source, P.LimitNode)
        and node.offset == 0
        and node.source.offset == 0
        and node.count is not None
        and node.source.count is not None
    ):
        return None
    return P.LimitNode(
        node.source.source, min(node.count, node.source.count)
    )


def rule_push_limit_through_project(node: P.PlanNode):
    """Limit(Project(x)) -> Project(Limit(x)) (reference:
    iterative/rule/PushLimitThroughProject.java): limiting first shrinks
    the projected batch's static shape."""
    if not (
        isinstance(node, P.LimitNode)
        and isinstance(node.source, P.ProjectNode)
        and node.offset == 0
    ):
        return None
    proj = node.source
    return P.ProjectNode(
        P.LimitNode(proj.source, node.count, node.offset), proj.assignments
    )


def rule_push_limit_through_union(node: P.PlanNode):
    """Limit(Union(c_i)) -> Limit(Union(Limit(c_i))) (reference:
    iterative/rule/PushLimitThroughUnion.java) — every branch needs at most
    `count` rows.  Fires once per shape (guarded by the inner limits)."""
    if not (
        isinstance(node, P.LimitNode)
        and isinstance(node.source, P.UnionNode)
        and node.offset == 0
        and node.count is not None
    ):
        return None
    u = node.source
    if all(
        isinstance(s, P.LimitNode) and s.count is not None
        and s.count <= node.count
        for s in u.sources
    ):
        return None
    capped = [
        s
        if isinstance(s, P.LimitNode)
        and s.count is not None
        and s.count <= node.count
        else P.LimitNode(s, node.count)
        for s in u.sources
    ]
    return P.LimitNode(
        P.UnionNode(capped, u.symbols, u.source_symbols), node.count
    )


def rule_limit_over_values(node: P.PlanNode):
    """Limit(Values) folds at plan time (reference:
    iterative/rule/EvaluateZeroLimit + constant-folded inputs)."""
    if not (
        isinstance(node, P.LimitNode)
        and isinstance(node.source, P.ValuesNode)
        and node.count is not None
    ):
        return None
    v = node.source
    lo = node.offset
    hi = lo + node.count
    if lo == 0 and hi >= len(v.rows):
        return v
    return P.ValuesNode(v.symbols, v.rows[lo:hi])


def rule_remove_redundant_sort(node: P.PlanNode):
    """Aggregation/MarkDistinct over a Sort (possibly through projections)
    drops the sort: grouped reduction is order-insensitive (reference:
    iterative/rule/RemoveRedundantSort.java family)."""
    if not isinstance(node, (P.AggregationNode, P.MarkDistinctNode)):
        return None
    # walk through row-preserving projections to find the sort
    chain = []
    cur = node.source
    while isinstance(cur, P.ProjectNode):
        chain.append(cur)
        cur = cur.source
    if not isinstance(cur, P.SortNode):
        return None
    rebuilt = cur.source
    for proj in reversed(chain):
        rebuilt = P.ProjectNode(rebuilt, proj.assignments)
    return node.with_children([rebuilt] + list(node.children[1:]))


def rule_remove_redundant_distinct(node: P.PlanNode):
    """DISTINCT (group-by-all-no-aggregates) over an aggregation already
    grouped on the same keys — possibly through a pure renaming projection
    — is a no-op (reference: iterative/rule/RemoveRedundantDistinct
    semantics)."""
    from trino_tpu.expr.ir import SymbolRef

    if not (
        isinstance(node, P.AggregationNode) and not node.aggregations
    ):
        return None
    src = node.source
    rename: dict = {}
    if isinstance(src, P.ProjectNode):
        if not all(isinstance(e, SymbolRef) for _, e in src.assignments):
            return None
        rename = {s.name: e.name for s, e in src.assignments}
        src = src.source
    if not isinstance(src, P.AggregationNode):
        return None
    outer_keys = {rename.get(s.name, s.name) for s in node.group_symbols}
    if outer_keys == {s.name for s in src.group_symbols}:
        return node.source  # the inner agg (through the projection if any)
    return None


def rule_merge_adjacent_projects(node: P.PlanNode):
    """Project(Project(x)) -> one Project with inlined assignments
    (reference: iterative/rule/InlineProjections.java).  Expressions are
    deterministic and XLA CSE dedupes any duplicated subtrees."""
    from trino_tpu.expr.ir import substitute_symbols

    if not (
        isinstance(node, P.ProjectNode)
        and isinstance(node.source, P.ProjectNode)
    ):
        return None
    inner = node.source
    mapping = {s.name: e for s, e in inner.assignments}
    merged = [
        (s, substitute_symbols(e, mapping)) for s, e in node.assignments
    ]
    return P.ProjectNode(inner.source, merged)


def rule_limit_to_topn(node: P.PlanNode):
    """Limit(Sort(x)) -> TopN (reference: iterative/rule/CreateTopN) —
    in case the syntactic lowering missed a shape (e.g. after other
    rewrites re-exposed it)."""
    if not (
        isinstance(node, P.LimitNode)
        and isinstance(node.source, P.SortNode)
        and node.count is not None
        and node.offset == 0
    ):
        return None
    s = node.source
    return P.TopNNode(s.source, s.orderings, node.count)


def optimize(plan: P.OutputNode, rules=None, catalogs=None, verify=None) -> P.OutputNode:
    from trino_tpu.planner.join_planning import (
        eliminate_cross_joins,
        push_filter_through_join,
        push_filter_through_semijoin,
    )
    from trino_tpu import verify as V

    # sanity-check the analyzer/logical-planner output BEFORE rewriting, so
    # a planning bug is named at its source, not blamed on the first rule
    # (reference: PlanSanityChecker.validateIntermediatePlan)
    vmode = V.resolve_mode(verify)
    if vmode != "off":
        V.enforce(V.check_plan(plan), vmode)

    if rules is None:
        rules = [
            rule_fold_constants,
            rule_merge_filters,
            rule_remove_trivial_filter,
            push_filter_through_semijoin,
            lambda n: eliminate_cross_joins(n, catalogs),
            push_filter_through_join,
            rule_push_filter_through_union,
            rule_push_filter_through_sample,
            rule_push_filter_through_project,
            rule_push_filter_through_aggregation,
            rule_push_filter_into_scan,
            rule_remove_identity_project,
            rule_merge_adjacent_projects,
            rule_mixed_distinct,
            rule_merge_limits,
            rule_push_limit_through_project,
            rule_push_limit_through_union,
            rule_limit_over_values,
            rule_limit_to_topn,
            rule_remove_redundant_sort,
            rule_remove_redundant_distinct,
        ]
    # iterate whole-tree passes to fixpoint: rules unlock each other (e.g.
    # cross-join elimination creates filters that then push into scans),
    # mirroring IterativeOptimizer's exploration loop.  Each iteration first
    # normalizes (merges the planner's cascaded single-conjunct filters) so
    # whole-predicate rules see the complete conjunct set.
    normalize = [rule_fold_constants, rule_merge_filters]
    stats: dict = {}
    prev = None
    for _ in range(10):
        plan = _rewrite_bottom_up(plan, normalize)
        plan = _rewrite_bottom_up(plan, rules, stats)
        fp = plan_fingerprint(plan)
        if fp == prev:
            break  # unchanged -> already validated last iteration
        prev = fp
        # every fixpoint iteration that CHANGED the plan re-validates: a
        # rule that broke an invariant is caught on the iteration that
        # fired it, while LAST_RULE_STATS still names the suspects
        if vmode != "off":
            V.enforce(V.check_plan(plan), vmode)
    global LAST_RULE_STATS
    LAST_RULE_STATS = stats
    from trino_tpu.planner.pruning import prune

    plan = prune(plan)
    if vmode != "off":
        V.enforce(V.check_plan(plan), vmode)
    # numeric licensing (verify/numeric.py): attach range-certificate
    # sum bounds to decimal sum/avg aggregations and window functions —
    # provably-exact single-plane i64 kernels downstream, no runtime fits
    # checks.  Proof-only: the pass never changes plan shape or results.
    from trino_tpu.verify.numeric import license_decimal_sums

    # capacity licensing FIRST (verify/capacity.py): a join whose build key
    # is proven unique gets a capacity_cert — the mesh runner compiles its
    # expand at the certified fixed capacity with no sizing gather — and
    # its fanout-aware row bounds are what let the decimal-sum licensing
    # below prove sums ABOVE joins.  Both passes are proof-only.
    from trino_tpu.verify.capacity import (
        check_capacity_certificates,
        license_join_capacities,
    )

    license_join_capacities(plan, catalogs)
    license_decimal_sums(plan, catalogs)
    if vmode == "strict":
        # the verifier rule holds the licensing pass itself to account: a
        # cert that re-derivation cannot justify fails right here
        V.enforce(check_capacity_certificates(plan, catalogs), vmode)
    assert isinstance(plan, P.OutputNode)
    return plan


def plan_fingerprint(node: P.PlanNode) -> str:
    from trino_tpu.planner.plan import plan_text

    return plan_text(node)
