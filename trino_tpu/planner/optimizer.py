"""Logical plan optimizer (reference: sql/planner/PlanOptimizers.java:267 and
the iterative rules under sql/planner/iterative/rule/).

Round-1 scope: a bottom-up rewrite driver with the rules that matter most for
the TPU execution model — constant folding, filter merging/pushdown into
scans, and identity-projection removal.  Cost-based join ordering and
distribution selection land with the distributed planner.
"""

from __future__ import annotations

from trino_tpu.expr.constant_folding import try_fold as fold
from trino_tpu.expr.ir import and_
from trino_tpu.planner import plan as P


def _rewrite_bottom_up(node: P.PlanNode, rules) -> P.PlanNode:
    kids = node.children
    if kids:
        node = node.with_children([_rewrite_bottom_up(c, rules) for c in kids])
    changed = True
    while changed:
        changed = False
        for rule in rules:
            out = rule(node)
            if out is not None:
                node = out
                changed = True
    return node


def rule_fold_constants(node: P.PlanNode):
    """Constant-fold expressions in filters/projections (reference:
    iterative/rule/SimplifyExpressions.java)."""
    if isinstance(node, P.FilterNode):
        folded = fold(node.predicate)
        if folded is not node.predicate and folded != node.predicate:
            return P.FilterNode(node.source, folded)
    if isinstance(node, P.ProjectNode):
        out = [(s, fold(e)) for s, e in node.assignments]
        if any(a is not b for (_, a), (_, b) in zip(out, node.assignments)):
            if [e.key() for _, e in out] != [e.key() for _, e in node.assignments]:
                return P.ProjectNode(node.source, out)
    return None


def rule_merge_filters(node: P.PlanNode):
    """Filter(Filter(x)) -> Filter(x) with AND (reference:
    iterative/rule/MergeFilters.java)."""
    if isinstance(node, P.FilterNode) and isinstance(node.source, P.FilterNode):
        return P.FilterNode(
            node.source.source, and_(node.source.predicate, node.predicate)
        )
    return None


def rule_push_filter_into_scan(node: P.PlanNode):
    """Filter(TableScan) -> TableScan with pushed predicate (reference:
    iterative/rule/PushPredicateIntoTableScan.java).  The scan operator fuses
    the predicate into its first device step, so filtering happens in the
    same XLA program as the host->device feed."""
    if isinstance(node, P.FilterNode) and isinstance(node.source, P.TableScanNode):
        scan = node.source
        pred = (
            node.predicate
            if scan.pushed_predicate is None
            else and_(scan.pushed_predicate, node.predicate)
        )
        return P.TableScanNode(
            scan.handle, scan.table_meta, scan.assignments, pred
        )
    return None


_MD_COUNTER = __import__("itertools").count()


def rule_mixed_distinct(node: P.PlanNode):
    """Rewrite mixed/multi-argument DISTINCT aggregates into MarkDistinct +
    FILTERed plain aggregates (reference: plan/MarkDistinctNode.java and
    the MultipleDistinctAggregationToMarkDistinct rule)."""
    from trino_tpu import types as T
    from trino_tpu.expr.ir import SymbolRef

    if not isinstance(node, P.AggregationNode) or node.step != "single":
        return None
    distincts = [(s, a) for s, a in node.aggregations if a.distinct]
    if not distincts:
        return None
    arg_sets = {tuple(x.key() for x in a.args) for _, a in distincts}
    if len(arg_sets) == 1 and all(a.distinct for _, a in node.aggregations):
        return None  # uniform shape: the execution-level pre-agg handles it
    if any(a.filter is not None for _, a in distincts):
        return None  # DISTINCT + FILTER: unsupported downstream
    if any(
        not all(isinstance(x, SymbolRef) for x in a.args) for _, a in distincts
    ):
        return None
    src = node.source
    marks: dict = {}
    new_aggs = []
    for s, a in node.aggregations:
        if not a.distinct:
            new_aggs.append((s, a))
            continue
        k = tuple(x.key() for x in a.args)
        if k not in marks:
            mark = P.Symbol(f"$distinct_{next(_MD_COUNTER)}", T.BOOLEAN)
            keys = list(node.group_symbols) + [
                P.Symbol(x.name, x.type) for x in a.args
            ]
            src = P.MarkDistinctNode(src, keys, mark)
            marks[k] = mark
        new_aggs.append(
            (s, P.Aggregation(a.function, a.args, False, marks[k].ref()))
        )
    return P.AggregationNode(src, node.group_symbols, new_aggs, node.step)


def rule_remove_identity_project(node: P.PlanNode):
    """Drop no-op projections (reference: iterative/rule/
    RemoveRedundantIdentityProjections.java)."""
    if isinstance(node, P.ProjectNode) and node.is_identity():
        src = node.source.outputs
        if [s.name for s in src] == [s.name for s, _ in node.assignments]:
            return node.source
    return None


def optimize(plan: P.OutputNode, rules=None, catalogs=None) -> P.OutputNode:
    from trino_tpu.planner.join_planning import (
        eliminate_cross_joins,
        push_filter_through_join,
        push_filter_through_semijoin,
    )

    if rules is None:
        rules = [
            rule_fold_constants,
            rule_merge_filters,
            push_filter_through_semijoin,
            lambda n: eliminate_cross_joins(n, catalogs),
            push_filter_through_join,
            rule_push_filter_into_scan,
            rule_remove_identity_project,
            rule_mixed_distinct,
        ]
    # iterate whole-tree passes to fixpoint: rules unlock each other (e.g.
    # cross-join elimination creates filters that then push into scans),
    # mirroring IterativeOptimizer's exploration loop.  Each iteration first
    # normalizes (merges the planner's cascaded single-conjunct filters) so
    # whole-predicate rules see the complete conjunct set.
    normalize = [rule_fold_constants, rule_merge_filters]
    prev = None
    for _ in range(10):
        plan = _rewrite_bottom_up(plan, normalize)
        plan = _rewrite_bottom_up(plan, rules)
        fp = plan_fingerprint(plan)
        if fp == prev:
            break
        prev = fp
    from trino_tpu.planner.pruning import prune

    plan = prune(plan)
    assert isinstance(plan, P.OutputNode)
    return plan


def plan_fingerprint(node: P.PlanNode) -> str:
    from trino_tpu.planner.plan import plan_text

    return plan_text(node)
