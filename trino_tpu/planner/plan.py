"""Logical plan nodes (reference: sql/planner/plan/*.java — PlanNode tree).

Symbol-based: every node outputs named, typed Symbols; expressions in nodes
are expr.ir trees over SymbolRef leaves.  The LocalExecutionPlanner maps
symbols to channels when building operator chains.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional, Sequence

from trino_tpu import types as T
from trino_tpu.connectors.api import TableHandle, TableMetadata
from trino_tpu.expr.ir import Expr, SymbolRef


@dataclass(frozen=True)
class Symbol:
    name: str
    type: T.Type

    def ref(self) -> SymbolRef:
        return SymbolRef(self.name, self.type)


class SymbolAllocator:
    """Unique symbol names (reference: sql/planner/SymbolAllocator.java)."""

    def __init__(self):
        self._counter = itertools.count()
        self._used: set[str] = set()

    def new(self, hint: str, type: T.Type) -> Symbol:
        base = "".join(c if (c.isalnum() or c == "_") else "_" for c in hint.lower()) or "expr"
        name = base
        while name in self._used:
            name = f"{base}_{next(self._counter)}"
        self._used.add(name)
        return Symbol(name, type)


#: process-wide PlanNode id allocator (reference: PlanNodeIdAllocator.java —
#: every node carries a unique id so the sanity checkers can name the exact
#: failing node and detect shared-subtree reuse after a bad rewrite)
_NODE_IDS = itertools.count(1)


class PlanNode:
    id: int = 0

    def __post_init__(self):
        # dataclass subclasses route through here; `id` is not a dataclass
        # field, so structural equality and repr are unaffected
        self.id = next(_NODE_IDS)

    @property
    def outputs(self) -> list[Symbol]:
        raise NotImplementedError

    @property
    def children(self) -> list["PlanNode"]:
        return []

    def with_children(self, children: Sequence["PlanNode"]) -> "PlanNode":
        raise NotImplementedError


@dataclass
class TableScanNode(PlanNode):
    handle: TableHandle
    table_meta: TableMetadata
    assignments: list  # [(Symbol, column_name)]
    #: conjuncts pushed into the connector scan (TupleDomain analog)
    pushed_predicate: Optional[Expr] = None

    @property
    def outputs(self):
        return [s for s, _ in self.assignments]

    def with_children(self, children):
        assert not children
        return self


@dataclass
class FilterNode(PlanNode):
    source: PlanNode
    predicate: Expr

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return FilterNode(children[0], self.predicate)


@dataclass
class ProjectNode(PlanNode):
    source: PlanNode
    assignments: list  # [(Symbol, Expr)]

    @property
    def outputs(self):
        return [s for s, _ in self.assignments]

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return ProjectNode(children[0], self.assignments)

    def is_identity(self) -> bool:
        src = self.source.outputs
        if len(src) != len(self.assignments):
            return False
        return all(
            isinstance(e, SymbolRef)
            and e.name == s.name
            and s.name == src_s.name
            for (s, e), src_s in zip(self.assignments, src)
        )


@dataclass
class Aggregation:
    """One aggregate: function name + argument expressions (symbol refs)."""

    function: str  # sum/count/count_star/avg/min/max/any_value/...
    args: list  # [Expr]; empty for count_star
    distinct: bool = False
    filter: Optional[Expr] = None
    param: object = None  # literal parameter (approx_percentile fraction)
    #: proof-licensed |partial sum| bound for decimal sum/avg: attached by
    #: verify.numeric.license_decimal_sums when a range certificate proves
    #: every partial sum fits int64 — the kernels then compile single-plane
    #: i64 segment sums with no runtime fits check (None = no proof)
    sum_bound: Optional[int] = None


@dataclass
class AggregationNode(PlanNode):
    source: PlanNode
    group_symbols: list  # [Symbol] (outputs for keys)
    aggregations: list  # [(Symbol, Aggregation)]
    step: str = "single"  # single | partial | final
    #: proof-licensed group-count certificate (verify.capacity): attached
    #: by license_join_capacities when the distinct group-key combination
    #: count is proven bounded — the mesh runner then licenses the fused
    #: exchange's slot capacity with NO [W, W] counts gather (None =
    #: runtime counts-sizing path)
    capacity_cert: Optional[object] = None

    @property
    def outputs(self):
        return list(self.group_symbols) + [s for s, _ in self.aggregations]

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return AggregationNode(
            children[0], self.group_symbols, self.aggregations, self.step,
            self.capacity_cert,
        )


@dataclass
class MarkDistinctNode(PlanNode):
    """Adds a boolean column marking the first occurrence of each distinct
    key combination (reference: plan/MarkDistinctNode.java +
    operator/MarkDistinctOperator.java).  Used to rewrite mixed DISTINCT
    aggregates into FILTERed plain aggregates."""

    source: PlanNode
    key_symbols: list  # [Symbol] (group keys + the distinct argument)
    mark: Symbol  # boolean output

    @property
    def outputs(self):
        return self.source.outputs + [self.mark]

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return MarkDistinctNode(children[0], self.key_symbols, self.mark)


@dataclass
class JoinNode(PlanNode):
    kind: str  # inner | left | right | full | cross
    left: PlanNode
    right: PlanNode
    criteria: list  # [(left Symbol, right Symbol)] equi-join keys
    filter: Optional[Expr] = None  # residual over combined symbols
    #: planner hint: 'partitioned' or 'broadcast' (AddExchanges decision)
    distribution: Optional[str] = None
    #: proof-licensed capacity certificate (verify.capacity): attached by
    #: license_join_capacities at the end of optimize() when the build-side
    #: key is proven unique — the mesh runner then compiles the expand at
    #: the certified fixed capacity with NO sizing gather, overflow flag,
    #: or speculative retry (None = runtime sizing path)
    capacity_cert: Optional[object] = None
    #: plan-decision ledger id of the distribution choice
    #: (telemetry/decisions): the runtime scopes this join's collectives
    #: under it so measured bytes join back to the recorded decision
    decision_id: Optional[str] = None

    @property
    def outputs(self):
        return self.left.outputs + self.right.outputs

    @property
    def children(self):
        return [self.left, self.right]

    def with_children(self, children):
        return JoinNode(
            self.kind, children[0], children[1], self.criteria, self.filter,
            self.distribution, self.capacity_cert, self.decision_id,
        )


@dataclass
class SemiJoinNode(PlanNode):
    """source rows marked by key membership in filtering source (reference:
    sql/planner/plan/SemiJoinNode.java).  negate=True -> anti join mark."""

    source: PlanNode
    filtering: PlanNode
    source_key: Symbol
    filtering_key: Symbol
    mark: Symbol  # boolean output symbol
    filter: Optional[Expr] = None  # extra correlated filter (over both sides)
    #: IN-subquery null semantics (mark NULL on null key / null in filtering
    #: side); False for EXISTS, whose mark is plain boolean
    null_aware: bool = True
    #: plan-decision ledger id of the distribution choice
    decision_id: Optional[str] = None

    @property
    def outputs(self):
        return self.source.outputs + [self.mark]

    @property
    def children(self):
        return [self.source, self.filtering]

    def with_children(self, children):
        return SemiJoinNode(
            children[0], children[1], self.source_key, self.filtering_key,
            self.mark, self.filter, self.null_aware, self.decision_id,
        )


@dataclass
class WindowFunction:
    """One window function call (reference: plan/WindowNode.Function)."""

    name: str
    args: list  # [Expr] (symbol refs)
    frame: str = "range"  # range | rows | full
    offset: int = 1  # lag/lead
    n_buckets_expr: object = None  # ntile bucket-count literal Expr
    default: object = None  # lag/lead default Expr
    # ROWS-frame literal bounds relative to current row (None = unbounded);
    # the default running frame is (None, 0)
    start_off: object = None
    end_off: object = 0
    ignore_nulls: bool = False  # lag/lead/first_value/last_value
    #: proof-licensed |frame sum| bound for decimal sum/avg over the
    #: window (see Aggregation.sum_bound); None = no proof
    sum_bound: Optional[int] = None


@dataclass
class WindowNode(PlanNode):
    """reference: sql/planner/plan/WindowNode.java."""

    source: PlanNode
    partition_by: list  # [Symbol]
    order_by: list  # [(Symbol, ascending, nulls_first)]
    functions: list  # [(Symbol, WindowFunction)]

    @property
    def outputs(self):
        return self.source.outputs + [s for s, _ in self.functions]

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return WindowNode(
            children[0], self.partition_by, self.order_by, self.functions
        )


@dataclass
class SortNode(PlanNode):
    source: PlanNode
    orderings: list  # [(Symbol, ascending, nulls_first)]

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return SortNode(children[0], self.orderings)


@dataclass
class TopNNode(PlanNode):
    source: PlanNode
    orderings: list
    count: int

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return TopNNode(children[0], self.orderings, self.count)


@dataclass
class LimitNode(PlanNode):
    source: PlanNode
    count: object  # int, or None for OFFSET without LIMIT
    offset: int = 0  # rows skipped before counting (reference: OffsetNode)

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return LimitNode(children[0], self.count, self.offset)


@dataclass
class ValuesNode(PlanNode):
    symbols: list
    rows: list  # python values in logical units

    @property
    def outputs(self):
        return self.symbols

    def with_children(self, children):
        assert not children
        return self


@dataclass
class UnionNode(PlanNode):
    sources: list
    symbols: list  # output symbols
    #: per-source mapping: list of symbol lists aligned with `symbols`
    source_symbols: list = field(default_factory=list)

    @property
    def outputs(self):
        return self.symbols

    @property
    def children(self):
        return list(self.sources)

    def with_children(self, children):
        return UnionNode(list(children), self.symbols, self.source_symbols)


@dataclass
class SampleNode(PlanNode):
    """reference: sql/planner/plan/SampleNode.java (BERNOULLI row sampling;
    SYSTEM falls back to the same row-level filter — split-level sampling
    has no meaning for generated/columnar splits)."""

    source: PlanNode
    ratio: float  # 0..1

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return SampleNode(children[0], self.ratio)


@dataclass
class MeasureSpec:
    """One MATCH_RECOGNIZE measure (reference: sql/planner/plan/
    PatternRecognitionNode.Measure — restricted to the navigations the
    operator evaluates: FIRST/LAST over a variable, CLASSIFIER(),
    MATCH_NUMBER(), and SQL aggregates over matched rows)."""

    kind: str  # first | last | classifier | match_number | agg
    var: Optional[str] = None  # pattern variable filter (None = any)
    source: Optional[Symbol] = None  # source column
    agg: Optional[str] = None  # count | sum | avg | min | max
    offset: int = 0  # FIRST/LAST logical offset


@dataclass
class PatternRecognitionNode(PlanNode):
    """reference: sql/planner/plan/PatternRecognitionNode.java."""

    source: PlanNode
    partition_by: list  # [Symbol]
    order_by: list  # [(Symbol, ascending, nulls_first)]
    defines: list  # [(var name, Expr over source symbols; prev/next Calls)]
    pattern: str
    measures: list  # [(Symbol, MeasureSpec)]
    rows_per_match: str = "one"
    after_match: str = "past_last"

    @property
    def outputs(self):
        if self.rows_per_match == "one":
            return list(self.partition_by) + [s for s, _ in self.measures]
        return self.source.outputs + [s for s, _ in self.measures]

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return PatternRecognitionNode(
            children[0], self.partition_by, self.order_by, self.defines,
            self.pattern, self.measures, self.rows_per_match, self.after_match,
        )


@dataclass
class UnnestNode(PlanNode):
    """Array expansion (reference: sql/planner/plan/UnnestNode.java +
    operator/unnest/UnnestOperator.java).  Source rows replicate per array
    element; multiple arrays zip; `ordinality` appends the element index."""

    source: PlanNode
    #: [(element output Symbol, array Expr over source symbols)]
    unnest: list
    ordinality: Optional["Symbol"] = None

    @property
    def outputs(self):
        out = list(self.source.outputs) + [s for s, _ in self.unnest]
        if self.ordinality is not None:
            out.append(self.ordinality)
        return out

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return UnnestNode(children[0], self.unnest, self.ordinality)


@dataclass
class EnforceSingleRowNode(PlanNode):
    """Scalar subquery guard (reference: plan/EnforceSingleRowNode.java)."""

    source: PlanNode

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return EnforceSingleRowNode(children[0])


@dataclass
class OutputNode(PlanNode):
    source: PlanNode
    column_names: list
    symbols: list

    @property
    def outputs(self):
        return self.symbols

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return OutputNode(children[0], self.column_names, self.symbols)


@dataclass
class ExchangeNode(PlanNode):
    """Data redistribution boundary (reference: plan/ExchangeNode.java).
    Inserted by the distributed planner; scope 'remote' fragments the plan."""

    source: PlanNode
    kind: str  # repartition | broadcast | gather | merge
    partition_symbols: list = field(default_factory=list)
    orderings: list = field(default_factory=list)  # for merge exchanges
    #: plan-decision ledger id of the placement choice that inserted this
    #: exchange; the fragmenter copies it onto the RemoteSourceNode so the
    #: runtime attributes the applied collective's bytes to the decision
    decision_id: Optional[str] = None

    @property
    def outputs(self):
        return self.source.outputs

    @property
    def children(self):
        return [self.source]

    def with_children(self, children):
        return ExchangeNode(
            children[0], self.kind, self.partition_symbols, self.orderings,
            self.decision_id,
        )


def copy_tree(node: PlanNode) -> PlanNode:
    """Structurally identical copy with fresh node instances (and ids) all
    the way down.  Used when a lowering needs the same input subtree in K
    places (grouping-set UNION branches): sharing one instance would break
    the tree-uniqueness invariant the sanity checkers enforce."""
    import dataclasses

    kids = node.children
    if kids:
        return node.with_children([copy_tree(c) for c in kids])
    return dataclasses.replace(node)


def walk(node: PlanNode):
    yield node
    for c in node.children:
        yield from walk(c)


def plan_text(node: PlanNode, indent: int = 0) -> str:
    """EXPLAIN rendering (reference role: planprinter/PlanPrinter.java)."""
    pad = "  " * indent
    name = type(node).__name__.replace("Node", "")
    detail = ""
    if isinstance(node, TableScanNode):
        h = node.handle
        cols = ", ".join(c for _, c in node.assignments)
        detail = f"[{h.catalog}.{h.schema}.{h.table}] columns=[{cols}]"
        if node.pushed_predicate is not None:
            detail += f" pushed={node.pushed_predicate!r}"
    elif isinstance(node, FilterNode):
        detail = f"[{node.predicate!r}]"
    elif isinstance(node, ProjectNode):
        detail = "[" + ", ".join(f"{s.name} := {e!r}" for s, e in node.assignments) + "]"
    elif isinstance(node, AggregationNode):
        keys = ", ".join(s.name for s in node.group_symbols)
        aggs = ", ".join(
            f"{s.name} := {a.function}({', '.join(map(repr, a.args))})"
            for s, a in node.aggregations
        )
        detail = f"[{node.step}] keys=[{keys}] aggs=[{aggs}]"
    elif isinstance(node, JoinNode):
        crit = ", ".join(f"{l.name} = {r.name}" for l, r in node.criteria)
        detail = f"[{node.kind}] criteria=[{crit}]"
        if node.filter is not None:
            detail += f" filter={node.filter!r}"
        if node.distribution:
            detail += f" dist={node.distribution}"
    elif isinstance(node, SemiJoinNode):
        detail = f"[{node.source_key.name} in {node.filtering_key.name} -> {node.mark.name}]"
    elif isinstance(node, (SortNode, TopNNode)):
        o = ", ".join(
            f"{s.name} {'ASC' if asc else 'DESC'}" for s, asc, _ in node.orderings
        )
        detail = f"[{o}]"
        if isinstance(node, TopNNode):
            detail += f" limit={node.count}"
    elif isinstance(node, LimitNode):
        detail = f"[{node.count}]"
    elif isinstance(node, WindowNode):
        fns = ", ".join(
            f"{s.name} := {f.name}({', '.join(map(repr, f.args))}) "
            f"frame={f.frame}[{f.start_off},{f.end_off}] off={f.offset}"
            for s, f in node.functions
        )
        part = ", ".join(s.name for s in node.partition_by)
        order = ", ".join(
            f"{s.name} {'ASC' if asc else 'DESC'}"
            for s, asc, _ in node.order_by
        )
        detail = f"[{fns}] partition=[{part}] order=[{order}]"
    elif isinstance(node, UnnestNode):
        items = ", ".join(f"{s.name} := {e!r}" for s, e in node.unnest)
        detail = f"[{items}]" + (
            " ordinality" if node.ordinality is not None else ""
        )
    elif isinstance(node, MarkDistinctNode):
        keys = ", ".join(s.name for s in node.key_symbols)
        detail = f"[{keys} -> {node.mark.name}]"
    elif isinstance(node, OutputNode):
        detail = "[" + ", ".join(node.column_names) + "]"
    elif isinstance(node, ExchangeNode):
        detail = f"[{node.kind}]" + (
            f" by=[{', '.join(s.name for s in node.partition_symbols)}]"
            if node.partition_symbols
            else ""
        )
    elif hasattr(node, "exchange_kind"):  # RemoteSourceNode (fragmenter)
        detail = f"[fragment {node.fragment_id}, {node.exchange_kind}]" + (
            f" by=[{', '.join(s.name for s in node.partition_symbols)}]"
            if node.partition_symbols
            else ""
        )
    lines = [f"{pad}{name}{detail}"]
    for c in node.children:
        lines.append(plan_text(c, indent + 1))
    return "\n".join(lines)
