"""Cardinality estimation for optimizer decisions.

Reference role: cost/ (FilterStatsCalculator.java, JoinStatsRule.java) — here
reduced to the row-count heuristics the join-order and build-side choices
need.  Connector-provided table statistics anchor the estimates (the tpch
connector knows exact row counts, mirroring plugin/trino-tpch/.../statistics).
"""

from __future__ import annotations

from trino_tpu.planner import plan as P

FILTER_SELECTIVITY = 0.25
AGG_GROUP_RATIO = 0.1


def estimate_rows(node: P.PlanNode, catalogs=None) -> float:
    if isinstance(node, P.TableScanNode):
        rows = _scan_rows(node, catalogs)
        if node.pushed_predicate is not None:
            rows *= FILTER_SELECTIVITY
        return rows
    if isinstance(node, P.FilterNode):
        return FILTER_SELECTIVITY * estimate_rows(node.source, catalogs)
    if isinstance(node, P.ProjectNode):
        return estimate_rows(node.source, catalogs)
    if isinstance(node, P.AggregationNode):
        if not node.group_symbols:
            return 1.0
        return max(1.0, AGG_GROUP_RATIO * estimate_rows(node.source, catalogs))
    if isinstance(node, P.JoinNode):
        l = estimate_rows(node.left, catalogs)
        r = estimate_rows(node.right, catalogs)
        if node.kind == "cross":
            return l * r
        if node.criteria:
            # equi join: assume FK-PK-ish — output near the larger input
            return max(l, r)
        return l * r * FILTER_SELECTIVITY
    if isinstance(node, P.SemiJoinNode):
        return estimate_rows(node.source, catalogs)
    if isinstance(node, (P.LimitNode, P.TopNNode)):
        return min(node.count, estimate_rows(node.source, catalogs))
    if isinstance(node, P.ValuesNode):
        return float(len(node.rows))
    if isinstance(node, P.UnionNode):
        return sum(estimate_rows(s, catalogs) for s in node.sources)
    if isinstance(node, P.EnforceSingleRowNode):
        return 1.0
    kids = node.children
    if kids:
        return estimate_rows(kids[0], catalogs)
    return 1000.0


def _scan_rows(node: P.TableScanNode, catalogs) -> float:
    if catalogs is not None:
        try:
            conn = catalogs.get(node.handle.catalog)
            stats = conn.metadata().table_statistics(
                node.handle.schema, node.handle.table
            )
            if stats is not None and stats.row_count is not None:
                return float(stats.row_count)
        except Exception:
            pass
    return 10000.0
