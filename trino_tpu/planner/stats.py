"""Cost-based cardinality estimation.

Reference role: core/trino-main/.../cost/ — StatsCalculator composed of
per-node rules (TableScanStatsRule, FilterStatsCalculator.java,
JoinStatsRule.java, AggregationStatsRule, UnionStatsRule ...), producing
PlanNodeStatsEstimate {outputRowCount, per-symbol SymbolStatsEstimate
{lowValue, highValue, nullsFraction, distinctValuesCount}}.

This is the same design, shrunk to the statistics the TPU engine's decisions
consume: join ordering (join_planning.py), join distribution + build-side
choice (fragmenter.py), and SHOW STATS.  Estimates flow bottom-up:

  * TableScan   -> connector TableStatistics (row count + column stats);
  * Filter      -> per-conjunct selectivity from column ndv/min-max/null
                   fraction (FilterStatsCalculator semantics: equality =
                   1/ndv, range = overlap fraction, IN = n/ndv, OR =
                   inclusion-exclusion, AND = product);
  * Join        -> l*r / max(ndv_left_key, ndv_right_key) per equi clause
                   (JoinStatsRule.calculateJoinSelectivity);
  * Aggregation -> min(rows, product of group-key ndv) groups.

Unknown stats degrade to the documented heuristic constants rather than
poisoning the whole subtree (Trino's UNKNOWN_FILTER_COEFFICIENT analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from trino_tpu.expr.ir import Call, Expr, Form, Literal, SpecialForm, SymbolRef
from trino_tpu.planner import plan as P

#: selectivity of a conjunct nothing could be derived for
#: (reference: FilterStatsCalculator.UNKNOWN_FILTER_COEFFICIENT = 0.9 —
#: we keep the historical 0.25 which benchmarks better for deep TPC-DS
#: trees where residuals are usually genuinely selective)
FILTER_SELECTIVITY = 0.25
#: fallback group-count ratio when group-key ndv is unknown
AGG_GROUP_RATIO = 0.1


@dataclass(frozen=True)
class ColStats:
    """Per-symbol statistics (reference: cost/SymbolStatsEstimate.java)."""

    ndv: Optional[float] = None
    low: Optional[float] = None  # numeric-comparable (dates = day numbers)
    high: Optional[float] = None
    null_fraction: float = 0.0

    def scaled(self, sel: float) -> "ColStats":
        """Shrink ndv for a row-count reduction by `sel` (distinct values
        survive per the birthday-problem cap Trino also applies: ndv can't
        exceed the new row count, handled by the caller)."""
        if self.ndv is None:
            return self
        return replace(self, ndv=max(1.0, self.ndv * min(1.0, sel * 2.0)))


@dataclass
class PlanStats:
    """reference: cost/PlanNodeStatsEstimate.java."""

    rows: float
    columns: dict = field(default_factory=dict)  # name -> ColStats

    def col(self, name: str) -> ColStats:
        return self.columns.get(name, ColStats())


def _as_num(v) -> Optional[float]:
    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    try:  # Decimal
        return float(v)
    except Exception:
        return None


def _range_fraction(cs: ColStats, lo: Optional[float], hi: Optional[float]) -> Optional[float]:
    """Fraction of [cs.low, cs.high] overlapped by [lo, hi]."""
    if cs.low is None or cs.high is None:
        return None
    width = cs.high - cs.low
    if width <= 0:
        # single-valued column: either it's in the range or not
        v = cs.low
        ok = (lo is None or v >= lo) and (hi is None or v <= hi)
        return 1.0 if ok else 1.0 / max(cs.ndv or 1.0, 1.0)
    a = cs.low if lo is None else max(cs.low, lo)
    b = cs.high if hi is None else min(cs.high, hi)
    if b < a:
        return 0.05  # out-of-range: keep a floor, stats may be stale
    return max(0.0, min(1.0, (b - a) / width))


def _conjunct_selectivity(c: Expr, stats: PlanStats):
    """-> (selectivity, {symbol: ColStats update}) for one conjunct.
    Mirrors FilterStatsCalculator's per-expression estimate methods."""
    # NOT e
    if isinstance(c, SpecialForm) and c.form == Form.NOT:
        s, _ = _conjunct_selectivity(c.args[0], stats)
        return max(0.0, 1.0 - s), {}
    # a OR b: inclusion-exclusion
    if isinstance(c, SpecialForm) and c.form == Form.OR:
        sel = 0.0
        prod = 1.0
        for a in c.args:
            s, _ = _conjunct_selectivity(a, stats)
            prod *= 1.0 - s
        sel = 1.0 - prod
        return min(1.0, sel), {}
    if isinstance(c, SpecialForm) and c.form == Form.AND:
        sel = 1.0
        upd: dict = {}
        for a in c.args:
            s, u = _conjunct_selectivity(a, stats)
            sel *= s
            upd.update(u)
        return sel, upd
    # IS NULL / IS NOT NULL
    if isinstance(c, SpecialForm) and c.form == Form.IS_NULL:
        v = c.args[0]
        if isinstance(v, SymbolRef):
            return stats.col(v.name).null_fraction or 0.05, {}
        return 0.05, {}
    # v IN (a, b, ...)
    if isinstance(c, SpecialForm) and c.form == Form.IN:
        v = c.args[0]
        items = c.args[1:]
        if isinstance(v, SymbolRef) and all(isinstance(i, Literal) for i in items):
            cs = stats.col(v.name)
            if cs.ndv:
                n = len({i.value for i in items})
                return min(1.0, n / cs.ndv), {v.name: replace(cs, ndv=float(n))}
        return min(1.0, 0.25 * max(1, len(items)) ** 0.5), {}
    # v BETWEEN lo AND hi
    if isinstance(c, SpecialForm) and c.form == Form.BETWEEN:
        v, lo, hi = c.args
        if (
            isinstance(v, SymbolRef)
            and isinstance(lo, Literal)
            and isinstance(hi, Literal)
        ):
            cs = stats.col(v.name)
            a, b = _as_num(lo.value), _as_num(hi.value)
            f = _range_fraction(cs, a, b)
            if f is not None:
                upd = replace(cs, low=a, high=b).scaled(f)
                return f, {v.name: upd}
        return FILTER_SELECTIVITY, {}
    if isinstance(c, Call) and len(c.args) == 2:
        a, b = c.args
        # normalize literal-on-left
        flip = {"$lt": "$gt", "$le": "$ge", "$gt": "$lt", "$ge": "$le",
                "$eq": "$eq", "$ne": "$ne"}
        if isinstance(a, Literal) and isinstance(b, SymbolRef) and c.name in flip:
            a, b = b, a
            name = flip[c.name]
        else:
            name = c.name
        if isinstance(a, SymbolRef) and isinstance(b, Literal):
            cs = stats.col(a.name)
            v = _as_num(b.value)
            if name == "$eq":
                if cs.ndv:
                    sel = 1.0 / cs.ndv
                    return sel, {a.name: ColStats(1.0, v, v, 0.0)}
                return FILTER_SELECTIVITY * 0.2, {}
            if name == "$ne":
                if cs.ndv:
                    return 1.0 - 1.0 / cs.ndv, {}
                return 0.9, {}
            if name in ("$lt", "$le") and v is not None:
                f = _range_fraction(cs, None, v)
                if f is not None:
                    return f, {a.name: replace(cs, high=v).scaled(f)}
            if name in ("$gt", "$ge") and v is not None:
                f = _range_fraction(cs, v, None)
                if f is not None:
                    return f, {a.name: replace(cs, low=v).scaled(f)}
            return FILTER_SELECTIVITY, {}
        if isinstance(a, SymbolRef) and isinstance(b, SymbolRef) and name == "$eq":
            # same-relation column equality: 1/max ndv
            n1, n2 = stats.col(a.name).ndv, stats.col(b.name).ndv
            m = max(n1 or 0.0, n2 or 0.0)
            return (1.0 / m if m else FILTER_SELECTIVITY), {}
    return FILTER_SELECTIVITY, {}


def filter_stats(stats: PlanStats, predicate: Expr) -> PlanStats:
    """reference: cost/FilterStatsCalculator.filterStats."""
    from trino_tpu.planner.join_planning import split_conjuncts_ir

    sel = 1.0
    cols = dict(stats.columns)
    for c in split_conjuncts_ir(predicate):
        s, upd = _conjunct_selectivity(c, stats)
        sel *= max(s, 1e-9)
        cols.update(upd)
    rows = max(1.0, stats.rows * min(1.0, sel))
    # cap every ndv at the new row count
    cols = {
        k: (replace(v, ndv=min(v.ndv, rows)) if v.ndv else v)
        for k, v in cols.items()
    }
    return PlanStats(rows, cols)


def _scan_stats(node: P.TableScanNode, catalogs) -> PlanStats:
    rows = 10000.0
    colstats: dict = {}
    if catalogs is not None:
        try:
            conn = catalogs.get(node.handle.catalog)
            ts = conn.metadata().table_statistics(node.handle.schema, node.handle.table)
            if ts is not None and ts.row_count is not None:
                rows = float(ts.row_count)
            if ts is not None:
                for sym, col in node.assignments:
                    c = ts.columns.get(col)
                    if c is not None:
                        colstats[sym.name] = ColStats(
                            ndv=(float(c.distinct_count) if c.distinct_count else None),
                            low=_as_num(c.low),
                            high=_as_num(c.high),
                            null_fraction=c.null_fraction or 0.0,
                        )
        except Exception:
            pass
    st = PlanStats(rows, colstats)
    if node.pushed_predicate is not None:
        st = filter_stats(st, node.pushed_predicate)
    return st


def compute_stats(node: P.PlanNode, catalogs=None, _cache=None) -> PlanStats:
    """Bottom-up stats derivation (reference: cost/ComposableStatsCalculator:
    one rule per node type, cached per plan node)."""
    if _cache is None:
        _cache = {}
    key = id(node)
    hit = _cache.get(key)
    if hit is not None:
        return hit
    st = _compute(node, catalogs, _cache)
    _cache[key] = st
    return st


def _compute(node, catalogs, cache) -> PlanStats:
    if isinstance(node, P.TableScanNode):
        return _scan_stats(node, catalogs)
    if isinstance(node, P.FilterNode):
        return filter_stats(compute_stats(node.source, catalogs, cache), node.predicate)
    if isinstance(node, P.ProjectNode):
        src = compute_stats(node.source, catalogs, cache)
        cols = {}
        for sym, e in node.assignments:
            if isinstance(e, SymbolRef):
                cols[sym.name] = src.col(e.name)
        return PlanStats(src.rows, cols)
    if isinstance(node, P.AggregationNode):
        src = compute_stats(node.source, catalogs, cache)
        if not node.group_symbols:
            return PlanStats(1.0, {})
        groups = 1.0
        known = True
        cols = {}
        for g in node.group_symbols:
            cs = src.col(g.name)
            if cs.ndv:
                groups *= cs.ndv
            else:
                known = False
            cols[g.name] = cs
        if known:
            rows = max(1.0, min(src.rows, groups))
        else:
            rows = max(1.0, AGG_GROUP_RATIO * src.rows)
        cols = {
            k: (replace(v, ndv=min(v.ndv, rows)) if v.ndv else v)
            for k, v in cols.items()
        }
        return PlanStats(rows, cols)
    if isinstance(node, P.JoinNode):
        l = compute_stats(node.left, catalogs, cache)
        r = compute_stats(node.right, catalogs, cache)
        cols = dict(l.columns)
        cols.update(r.columns)
        if node.kind == "cross" and not node.criteria:
            return PlanStats(l.rows * r.rows, cols)
        if node.criteria:
            # reference: JoinStatsRule.calculateJoinSelectivity — per equi
            # clause sel = 1/max(ndv_l, ndv_r); clauses beyond the first are
            # dampened (PlanNodeStatsEstimateMath.UNKNOWN_FILTER dampening)
            rows = l.rows * r.rows
            sels = []
            for lk, rk in node.criteria:
                nl = l.col(lk.name).ndv
                nr = r.col(rk.name).ndv
                m = max(nl or 0.0, nr or 0.0)
                if m:
                    sels.append(1.0 / m)
                else:
                    sels.append(1.0 / max(l.rows, r.rows, 1.0))
            sels.sort()
            damp = 1.0
            for i, s in enumerate(sels):
                rows *= s ** (damp if i == 0 else 0.5 ** i)
            rows = max(1.0, rows)
            if node.filter is not None:
                rows = max(1.0, rows * FILTER_SELECTIVITY)
            if node.kind in ("left", "full"):
                rows = max(rows, l.rows)
            if node.kind in ("right", "full"):
                rows = max(rows, r.rows)
            cols = {
                k: (replace(v, ndv=min(v.ndv, rows)) if v.ndv else v)
                for k, v in cols.items()
            }
            return PlanStats(rows, cols)
        # non-equi join
        rows = max(1.0, l.rows * r.rows * FILTER_SELECTIVITY)
        return PlanStats(rows, cols)
    if isinstance(node, P.SemiJoinNode):
        src = compute_stats(node.source, catalogs, cache)
        return PlanStats(src.rows, dict(src.columns))
    if isinstance(node, (P.LimitNode, P.TopNNode)):
        src = compute_stats(node.source, catalogs, cache)
        return PlanStats(min(float(node.count), src.rows), dict(src.columns))
    if isinstance(node, P.ValuesNode):
        return PlanStats(float(len(node.rows)), {})
    if isinstance(node, P.UnionNode):
        return PlanStats(
            sum(compute_stats(s, catalogs, cache).rows for s in node.sources), {}
        )
    if isinstance(node, P.EnforceSingleRowNode):
        return PlanStats(1.0, {})
    if isinstance(node, P.SampleNode):
        src = compute_stats(node.source, catalogs, cache)
        rows = max(1.0, src.rows * node.ratio)
        cols = {
            k: (replace(v, ndv=min(v.ndv, rows)) if v.ndv else v)
            for k, v in src.columns.items()
        }
        return PlanStats(rows, cols)
    kids = node.children
    if kids:
        src = compute_stats(kids[0], catalogs, cache)
        return PlanStats(src.rows, dict(src.columns))
    return PlanStats(1000.0, {})


def estimate_rows(node: P.PlanNode, catalogs=None) -> float:
    """Row-count-only view (what fragmenter's distribution choice reads)."""
    return compute_stats(node, catalogs).rows
