"""AST -> logical plan (reference: sql/planner/LogicalPlanner.java:155,
QueryPlanner.java, RelationPlanner.java, SubqueryPlanner — combined).

Planning is analysis-driven: expressions are typed while the plan is built.
Subqueries decorrelate on the way in: correlated equi-conjuncts become join
criteria (scalar aggregates -> grouped LEFT JOIN; EXISTS/IN -> semi join with
mark), mirroring the reference's TransformCorrelated* rule family but done
directly at plan time.
"""

from __future__ import annotations

from typing import Optional

from trino_tpu import types as T
from trino_tpu.connectors.api import CatalogManager, TableHandle
from trino_tpu.expr import ir
from trino_tpu.expr.ir import Call, Expr, Form, Literal, SpecialForm, SymbolRef
from trino_tpu.planner import plan as P
from trino_tpu.planner.analyzer import (
    AnalysisError,
    ExprAnalyzer,
    Field,
    Scope,
    collect_aggregates,
    split_conjuncts,
)
from trino_tpu.planner.functions import AGG_FUNCS, REWRITTEN_AGGS, agg_result_type
from trino_tpu.sql import ast


class RelationPlan:
    def __init__(self, node: P.PlanNode, fields: list[Field]):
        self.node = node
        self.fields = fields

    def scope(self, parent: Optional[Scope] = None) -> Scope:
        return Scope(self.fields, parent)


class Session:
    """Minimal session state (reference: Session.java)."""

    def __init__(self, catalog: Optional[str] = None, schema: Optional[str] = None):
        self.catalog = catalog
        self.schema = schema
        self.properties: dict = {}


class LogicalPlanner:
    def __init__(self, catalogs: CatalogManager, session: Session, views=None):
        self.catalogs = catalogs
        self.session = session
        self.alloc = P.SymbolAllocator()
        #: (catalog, schema, name) -> view Query AST; views expand inline at
        #: plan time (reference: sql/analyzer view expansion in
        #: StatementAnalyzer.Visitor.visitTable)
        self.views = views or {}
        #: views currently being expanded (cycle detection: the reference
        #: raises VIEW_IS_RECURSIVE instead of recursing to death)
        self._view_stack: set = set()

    # -- statements ----------------------------------------------------------

    def plan(self, query: ast.Query) -> P.OutputNode:
        rp, names = self.plan_query(query, outer=None, ctes={})
        return P.OutputNode(rp.node, names, [f.symbol for f in rp.fields])

    # -- queries -------------------------------------------------------------

    def plan_query(self, q: ast.Query, outer: Optional[Scope], ctes: dict):
        ctes = dict(ctes)
        for w in q.ctes:
            ctes[w.name] = w
        rp, names = self.plan_query_body(q.body, outer, ctes)
        # ORDER BY / LIMIT at query level
        if q.order_by or q.limit is not None or q.offset:
            rp, names = self._apply_order_limit(rp, names, q, outer, ctes)
        return rp, names

    def _apply_order_limit(self, rp, names, q: ast.Query, outer, ctes):
        node = rp.node
        if q.order_by:
            orderings = []
            hidden: list = []  # (Symbol, Expr) computed sort keys
            hidden_src: list = []  # sort keys over PRE-projection symbols
            # expressions in ORDER BY see the output columns under their
            # display names (reference: Scope of the query's output)
            scope = Scope(
                [
                    Field(n, f.symbol, f.alias)
                    for f, n in zip(rp.fields, names)
                ],
                outer,
            )
            by_alias = {}
            for f, n in zip(rp.fields, names):
                by_alias.setdefault(n, f.symbol)
            for item in q.order_by:
                sym = None
                if isinstance(item.expr, ast.Identifier) and len(item.expr.parts) == 1:
                    sym = by_alias.get(item.expr.parts[0])
                if sym is None and isinstance(item.expr, ast.NumberLiteral):
                    sym = rp.fields[int(item.expr.text) - 1].symbol
                if sym is None:
                    try:
                        e = ExprAnalyzer(scope).analyze(item.expr)
                    except AnalysisError:
                        e = None
                    if isinstance(e, SymbolRef):
                        sym = P.Symbol(e.name, e.type)
                    elif e is not None:
                        # computed sort key over output columns: pre-project
                        # a hidden symbol, sort on it, drop it afterwards
                        # (reference: QueryPlanner ORDER BY synthetic symbols)
                        sym = self.alloc.new("orderby", e.type)
                        hidden.append((sym, e))
                if (
                    sym is None
                    and isinstance(item.expr, ast.Identifier)
                    and len(item.expr.parts) >= 2
                ):
                    # qualified ref (dt.d_year) whose qualifier the output
                    # scope no longer tracks: accept only when an output item
                    # carries the same source alias + display name (propagated
                    # by _plan_select_items); never bind a bare-name match to
                    # a different table's column
                    qual, name = item.expr.parts[-2], item.expr.parts[-1]
                    matches = [
                        f.symbol for f, n in zip(rp.fields, names)
                        if f.alias == qual
                        and (
                            n == name
                            # SELECT a.col AS alias ... ORDER BY a.col: the
                            # display name moved, but the Field remembers the
                            # source column
                            or f.source_name == name
                        )
                    ]
                    if len(matches) == 1:
                        sym = matches[0]
                if sym is None:
                    # ORDER BY repeating an output item's source expression
                    # (`ORDER BY substr(s_city, 1, 30)`, `ORDER BY sum(x)`) or
                    # the pre-rename source column of an aliased item —
                    # frozen-dataclass equality gives the structural match
                    matches = [
                        f.symbol
                        for f in rp.fields
                        if f.source_expr is not None
                        and f.source_expr == item.expr
                    ]
                    if not matches and isinstance(item.expr, ast.Identifier):
                        nm = item.expr.parts[-1]
                        matches = [
                            f.symbol for f in rp.fields if f.source_name == nm
                        ]
                        if len(matches) > 1:
                            raise AnalysisError(
                                f"ORDER BY column is ambiguous: {nm}"
                            )
                    if len(matches) == 1:
                        sym = matches[0]
                if sym is None and getattr(rp, "source_fields", None):
                    # ORDER BY a source column that is NOT an output item
                    # (`SELECT o_orderkey FROM orders ORDER BY o_totalprice`):
                    # resolve against the pre-projection scope and sort on a
                    # hidden symbol pushed into the final projection
                    # (reference: QueryPlanner's ORDER BY scope = source +
                    # output)
                    try:
                        e = ExprAnalyzer(
                            Scope(rp.source_fields, outer)
                        ).analyze(item.expr)
                    except AnalysisError:
                        e = None
                    if e is not None:
                        if isinstance(e, SymbolRef):
                            sym = P.Symbol(e.name, e.type)
                        else:
                            sym = self.alloc.new("orderby", e.type)
                        hidden_src.append((sym, e))
                if sym is None:
                    raise AnalysisError(
                        "ORDER BY expression must be an output column here: "
                        f"{item.expr!r}"
                    )
                nf = item.nulls_first
                if nf is None:
                    nf = not item.ascending  # reference default: NULLS LAST asc, FIRST desc
                orderings.append((sym, item.ascending, nf))
            if hidden_src:
                # push hidden source-column sort keys into the output
                # projection (its source still carries those symbols)
                assert isinstance(node, P.ProjectNode), node
                node = P.ProjectNode(
                    node.source,
                    list(node.assignments)
                    + [
                        (s, e)
                        for s, e in hidden_src
                        if not any(s.name == o.name for o, _ in node.assignments)
                    ],
                )
            if hidden:
                node = P.ProjectNode(
                    node,
                    [(f.symbol, f.symbol.ref()) for f in rp.fields]
                    + [(s, s.ref()) for s, _ in hidden_src]
                    + hidden,
                )
            for osym, *_rest in orderings:
                if not osym.type.orderable:
                    raise AnalysisError(
                        f"ORDER BY on non-orderable type {osym.type.name}"
                    )
            if q.limit is not None and not q.offset:
                node = P.TopNNode(node, orderings, q.limit)
            else:
                node = P.SortNode(node, orderings)
                if q.limit is not None or q.offset:
                    node = P.LimitNode(node, q.limit, q.offset or 0)
            if hidden or hidden_src:
                node = P.ProjectNode(
                    node, [(f.symbol, f.symbol.ref()) for f in rp.fields]
                )
        elif q.limit is not None or q.offset:
            node = P.LimitNode(node, q.limit, q.offset or 0)
        return RelationPlan(node, rp.fields), names

    def plan_query_body(self, body: ast.Node, outer, ctes):
        if isinstance(body, ast.QuerySpec):
            return self.plan_query_spec(body, outer, ctes)
        if isinstance(body, ast.SetOp):
            return self.plan_set_op(body, outer, ctes)
        if isinstance(body, ast.ValuesRelation):
            rp = self.plan_values(body)
            return rp, [f.name for f in rp.fields]
        if isinstance(body, ast.Query):
            return self.plan_query(body, outer, ctes)
        if isinstance(body, ast.TableRef):
            rp = self.plan_relation(body, outer, ctes)
            return rp, [f.name for f in rp.fields]
        raise AnalysisError(f"unsupported query body {type(body).__name__}")

    def plan_set_op(self, s: ast.SetOp, outer, ctes):
        lrp, lnames = self.plan_query_body(s.left, outer, ctes)
        rrp, rnames = self.plan_query_body(s.right, outer, ctes)
        if len(lrp.fields) != len(rrp.fields):
            raise AnalysisError(
                f"{s.op.upper()} inputs must have the same arity"
            )
        if s.op == "union":
            out_syms = []
            for lf, rf in zip(lrp.fields, rrp.fields):
                t = T.common_super_type(lf.symbol.type, rf.symbol.type)
                out_syms.append(self.alloc.new(lf.name, t))
            node = P.UnionNode(
                [lrp.node, rrp.node],
                out_syms,
                [[f.symbol for f in lrp.fields], [f.symbol for f in rrp.fields]],
            )
            if not s.all:
                node = P.AggregationNode(node, list(out_syms), [])
            fields = [Field(n, s_) for n, s_ in zip(lnames, out_syms)]
            return RelationPlan(node, fields), lnames
        # INTERSECT / EXCEPT: lowered to a tagged UNION ALL + per-side
        # counts + filter (reference: the ImplementIntersectAsUnion /
        # ImplementExceptAsUnion rules under sql/planner/iterative/rule/ +
        # SqlBase.g4:244-245).  ALL (bag) semantics ride the same plan with
        # a per-side occurrence number (row_number over all columns): the
        # k-th copy of a value on the left pairs with the k-th copy on the
        # right, so the distinct machinery over (columns..., occ) yields
        # exactly min(l, r) / max(l - r, 0) copies.
        sides = []
        for rp in (lrp, rrp):
            node_in = rp.node
            syms = [f.symbol for f in rp.fields]
            if s.all:
                occ = self.alloc.new("occ", T.BIGINT)
                node_in = P.WindowNode(
                    node_in,
                    list(syms),
                    [],
                    [(occ, P.WindowFunction("row_number", []))],
                )
                syms = syms + [occ]
            side = self.alloc.new("side", T.BIGINT)
            tag = P.ProjectNode(
                node_in,
                [(sy, sy.ref()) for sy in syms]
                + [(side, Literal(len(sides), T.BIGINT))],
            )
            sides.append((tag, syms + [side]))
        out_syms = []
        for lf, rf in zip(lrp.fields, rrp.fields):
            t = T.common_super_type(lf.symbol.type, rf.symbol.type)
            out_syms.append(self.alloc.new(lf.name, t))
        group_syms = list(out_syms)
        if s.all:
            occ_out = self.alloc.new("occ", T.BIGINT)
            group_syms.append(occ_out)
        side_sym = self.alloc.new("side", T.BIGINT)
        union = P.UnionNode(
            [n for n, _ in sides],
            group_syms + [side_sym],
            [syms for _, syms in sides],
        )
        lcnt = self.alloc.new("lcnt", T.BIGINT)
        rcnt = self.alloc.new("rcnt", T.BIGINT)
        aggs = [
            (
                lcnt,
                P.Aggregation(
                    "count_star",
                    [],
                    filter=ir.comparison(
                        "=", side_sym.ref(), Literal(0, T.BIGINT)
                    ),
                ),
            ),
            (
                rcnt,
                P.Aggregation(
                    "count_star",
                    [],
                    filter=ir.comparison(
                        "=", side_sym.ref(), Literal(1, T.BIGINT)
                    ),
                ),
            ),
        ]
        agg = P.AggregationNode(union, group_syms, aggs)
        both = ir.comparison(">", lcnt.ref(), Literal(0, T.BIGINT))
        other = (
            ir.comparison(">", rcnt.ref(), Literal(0, T.BIGINT))
            if s.op == "intersect"
            else ir.comparison("=", rcnt.ref(), Literal(0, T.BIGINT))
        )
        filt = P.FilterNode(agg, ir.and_(both, other))
        proj = P.ProjectNode(filt, [(sym, sym.ref()) for sym in out_syms])
        fields = [Field(n, s_) for n, s_ in zip(lnames, out_syms)]
        return RelationPlan(proj, fields), lnames

    def plan_values(self, v: ast.ValuesRelation) -> RelationPlan:
        scope = Scope([])
        an = ExprAnalyzer(scope)
        rows = []
        col_types: list[T.Type] = []
        for row in v.rows:
            vals = []
            for i, e in enumerate(row):
                lit = an.analyze(e)
                if not isinstance(lit, Literal):
                    from trino_tpu.expr.constant_folding import try_fold

                    lit = try_fold(lit)
                    if not isinstance(lit, Literal):
                        raise AnalysisError("VALUES entries must be constant")
                if i >= len(col_types):
                    col_types.append(lit.type)
                else:
                    col_types[i] = T.common_super_type(col_types[i], lit.type)
                vals.append(lit.value)
            rows.append(vals)
        syms = [
            self.alloc.new(f"_col{i}", t if t != T.UNKNOWN else T.BIGINT)
            for i, t in enumerate(col_types)
        ]
        fields = [Field(s.name, s) for s in syms]
        return RelationPlan(P.ValuesNode(syms, rows), fields)

    # -- relations -----------------------------------------------------------

    def plan_relation(self, rel: ast.Node, outer, ctes) -> RelationPlan:
        if isinstance(rel, ast.TableRef):
            if len(rel.name) == 1 and rel.name[0] in ctes:
                w = ctes[rel.name[0]]
                sub_ctes = {k: v for k, v in ctes.items() if k != rel.name[0]}
                rp, names = self.plan_query(w.query, outer, sub_ctes)
                colnames = list(w.column_names) or names
                fields = [
                    Field(n, f.symbol, rel.name[0])
                    for n, f in zip(colnames, rp.fields)
                ]
                return RelationPlan(rp.node, fields)
            vkey = self.resolve_table_name(rel.name)
            vq = self.views.get(vkey)
            if vq is not None:
                # view expansion: plan the stored definition inline
                if vkey in self._view_stack:
                    raise AnalysisError(
                        f"view {'.'.join(rel.name)} is recursive"
                    )
                self._view_stack.add(vkey)
                try:
                    rp, names = self.plan_query(vq, None, {})
                finally:
                    self._view_stack.discard(vkey)
                fields = [
                    Field(n, f.symbol, rel.name[-1])
                    for n, f in zip(names, rp.fields)
                ]
                return RelationPlan(rp.node, fields)
            return self.plan_table_scan(rel)
        if isinstance(rel, ast.AliasedRelation):
            rp = self.plan_relation(rel.relation, outer, ctes)
            names = list(rel.column_aliases) or [f.name for f in rp.fields]
            fields = [
                Field(n, f.symbol, rel.alias) for n, f in zip(names, rp.fields)
            ]
            return RelationPlan(rp.node, fields)
        if isinstance(rel, ast.SubqueryRelation):
            rp, names = self.plan_query(rel.query, outer, ctes)
            fields = [Field(n, f.symbol) for n, f in zip(names, rp.fields)]
            return RelationPlan(rp.node, fields)
        if isinstance(rel, ast.MatchRecognize):
            return self.plan_match_recognize(rel, outer, ctes)
        if isinstance(rel, ast.TableSample):
            if not (0.0 <= rel.percent <= 100.0):
                raise AnalysisError(
                    "sample percentage must be between 0 and 100, "
                    f"got {rel.percent}"
                )
            src = self.plan_relation(rel.relation, outer, ctes)
            return RelationPlan(
                P.SampleNode(src.node, rel.percent / 100.0), src.fields
            )
        if isinstance(rel, ast.Join):
            return self.plan_join(rel, outer, ctes)
        if isinstance(rel, ast.ValuesRelation):
            return self.plan_values(rel)
        if isinstance(rel, ast.Unnest):
            # standalone FROM UNNEST(...): unnest over a one-row source
            single = RelationPlan(P.ValuesNode([], [()]), [])
            return self.plan_unnest(rel, single, outer, ctes, alias=None)
        if isinstance(rel, ast.TableFunctionCall):
            from trino_tpu.planner.table_functions import TABLE_FUNCTIONS

            tf = TABLE_FUNCTIONS.get(rel.name)
            if tf is None:
                raise AnalysisError(f"table function not found: {rel.name}")
            return tf.plan(self, list(rel.args), outer, ctes)
        raise AnalysisError(f"unsupported relation {type(rel).__name__}")

    def plan_match_recognize(
        self, mr: ast.MatchRecognize, outer, ctes
    ) -> RelationPlan:
        """relation MATCH_RECOGNIZE (...) -> PatternRecognitionNode
        (reference: sql/analyzer's pattern-recognition analysis +
        RelationPlanner.visitPatternRecognitionRelation)."""
        import dataclasses

        from trino_tpu.ops.pattern import parse_pattern, pattern_variables

        src = self.plan_relation(mr.relation, outer, ctes)
        scope = src.scope()
        an = ExprAnalyzer(scope)
        pvars = set(pattern_variables(parse_pattern(mr.pattern)))

        def make_strip(allowed):
            """Pattern-variable qualifiers (A.price) resolve to the source
            column.  Inside DEFINE only the variable being defined may
            qualify (a reference to ANOTHER variable means 'the last row
            matched to it' — the vectorized evaluator cannot honor that, so
            it must be an error, never a silently-wrong current-row read)."""

            def strip_qualifiers(node):
                if not isinstance(node, ast.Node):
                    return node
                if (
                    isinstance(node, ast.Identifier)
                    and len(node.parts) > 1
                    and node.parts[0].lower() in pvars
                ):
                    q = node.parts[0].lower()
                    if allowed is not None and q not in allowed:
                        raise AnalysisError(
                            f"cross-variable reference {q}.{node.parts[1]} "
                            "in DEFINE is not supported (only the variable "
                            "being defined may qualify)"
                        )
                    node = ast.Identifier(tuple(node.parts[1:]))
                kwargs = {}
                for f in dataclasses.fields(node):
                    v = getattr(node, f.name)
                    if isinstance(v, ast.Node):
                        kwargs[f.name] = strip_qualifiers(v)
                    elif isinstance(v, tuple):
                        kwargs[f.name] = tuple(
                            strip_qualifiers(x) if isinstance(x, ast.Node) else x
                            for x in v
                        )
                    else:
                        kwargs[f.name] = v
                return dataclasses.replace(node, **kwargs)

            return strip_qualifiers

        strip_qualifiers = make_strip(None)

        def col_symbol(e: ast.Node, what: str) -> P.Symbol:
            ir_e = an.analyze(strip_qualifiers(e))
            if not isinstance(ir_e, SymbolRef):
                raise AnalysisError(
                    f"MATCH_RECOGNIZE {what} must be a column reference"
                )
            return P.Symbol(ir_e.name, ir_e.type)

        partition_by = [col_symbol(e, "PARTITION BY") for e in mr.partition_by]
        order_by = [
            (col_symbol(it.expr, "ORDER BY"), it.ascending, it.nulls_first)
            for it in mr.order_by
        ]
        defines = [
            (v.lower(), an.analyze(make_strip({v.lower()})(cond)))
            for v, cond in mr.defines
        ]
        for v, _ in defines:
            if v not in pvars:
                raise AnalysisError(
                    f"DEFINE variable {v} not used in PATTERN"
                )
        measures = []
        for e, name in mr.measures:
            spec, out_t = self._analyze_measure(e, pvars, an, strip_qualifiers)
            measures.append((P.Symbol(name, out_t), spec))
        node = P.PatternRecognitionNode(
            src.node,
            partition_by,
            order_by,
            defines,
            mr.pattern,
            measures,
            mr.rows_per_match,
            mr.after_match,
        )
        if mr.rows_per_match == "one":
            fields = [Field(s.name, s) for s in partition_by] + [
                Field(s.name, s) for s, _ in measures
            ]
        else:
            fields = list(src.fields) + [
                Field(s.name, s) for s, _ in measures
            ]
        return RelationPlan(node, fields)

    def _analyze_measure(self, e: ast.Node, pvars, an, strip):
        """-> (MeasureSpec, out_type).  Supported shapes (reference:
        PatternRecognitionNode.Measure): FIRST/LAST(V.col [, offset]),
        V.col / col (= LAST), CLASSIFIER(), MATCH_NUMBER(), and
        count/sum/avg/min/max(V.col | col)."""
        from trino_tpu.planner.functions import agg_result_type

        def var_and_col(arg):
            var = None
            if isinstance(arg, ast.Identifier) and len(arg.parts) > 1:
                q = arg.parts[0].lower()
                if q in pvars:
                    var = q
            ir_e = an.analyze(strip(arg))
            if not isinstance(ir_e, SymbolRef):
                raise AnalysisError(
                    "MATCH_RECOGNIZE measures support column navigation, "
                    "CLASSIFIER(), MATCH_NUMBER() and simple aggregates"
                )
            return var, P.Symbol(ir_e.name, ir_e.type)

        if isinstance(e, ast.FunctionCall):
            fn = e.name.lower()
            if fn == "classifier":
                return P.MeasureSpec("classifier"), T.VARCHAR
            if fn == "match_number":
                return P.MeasureSpec("match_number"), T.BIGINT
            if fn in ("first", "last"):
                var, sym = var_and_col(e.args[0])
                off = 0
                if len(e.args) > 1:
                    off = int(e.args[1].text)
                return P.MeasureSpec(fn, var, sym, offset=off), sym.type
            if fn in ("count", "sum", "avg", "min", "max"):
                if fn == "count" and (e.is_star or not e.args):
                    return P.MeasureSpec("agg", None, None, agg="count"), T.BIGINT
                var, sym = var_and_col(e.args[0])
                return (
                    P.MeasureSpec("agg", var, sym, agg=fn),
                    agg_result_type(fn, sym.type),
                )
        if isinstance(e, ast.Identifier):
            var, sym = var_and_col(e)
            return P.MeasureSpec("last", var, sym), sym.type
        raise AnalysisError(
            f"unsupported MATCH_RECOGNIZE measure: {e!r}"
        )

    def plan_unnest(
        self,
        u: ast.Unnest,
        left: RelationPlan,
        outer,
        ctes,
        alias: Optional[str] = None,
        column_aliases: tuple = (),
        keep_left_fields: bool = True,
    ) -> RelationPlan:
        """UNNEST relation, possibly correlated to `left` (the relation that
        precedes it in the FROM list).  Reference:
        sql/planner/QueryPlanner.java's planCrossJoinUnnest."""
        scope = left.scope(outer)
        an = ExprAnalyzer(scope)
        unnest = []
        elem_fields = []
        for i, e in enumerate(u.exprs):
            expr = an.analyze(e)
            if not isinstance(expr.type, T.ArrayType):
                raise AnalysisError(
                    f"UNNEST argument must be an array, got {expr.type.name}"
                )
            name = (
                column_aliases[i]
                if i < len(column_aliases)
                else _name_hint(e)
            )
            sym = self.alloc.new(name, expr.type.element)
            unnest.append((sym, expr))
            elem_fields.append(Field(name, sym, alias))
        ord_sym = None
        if u.with_ordinality:
            oname = (
                column_aliases[len(u.exprs)]
                if len(column_aliases) > len(u.exprs)
                else "ordinality"
            )
            ord_sym = self.alloc.new(oname, T.BIGINT)
            elem_fields.append(Field(oname, ord_sym, alias))
        node = P.UnnestNode(left.node, unnest, ord_sym)
        fields = (list(left.fields) if keep_left_fields else []) + elem_fields
        return RelationPlan(node, fields)

    def resolve_table_name(self, parts: tuple) -> tuple:
        """Name parts -> (catalog, schema, table) with session defaults."""
        if len(parts) == 3:
            return tuple(parts)
        if len(parts) == 2:
            return (self.session.catalog,) + tuple(parts)
        return (self.session.catalog, self.session.schema, parts[0])

    def plan_table_scan(self, ref: ast.TableRef) -> RelationPlan:
        parts = ref.name
        catalog, schema, table = self.resolve_table_name(parts)
        if catalog is None or schema is None:
            raise AnalysisError(f"table {'.'.join(parts)}: no current catalog/schema")
        conn = self.catalogs.get(catalog)
        meta = conn.metadata().table_metadata(schema, table)
        handle = TableHandle(catalog, schema, table)
        assignments = []
        fields = []
        for cm in meta.columns:
            sym = self.alloc.new(cm.name, cm.type)
            assignments.append((sym, cm.name))
            fields.append(Field(cm.name, sym, table))
        return RelationPlan(P.TableScanNode(handle, meta, assignments), fields)

    def plan_join(self, j: ast.Join, outer, ctes) -> RelationPlan:
        left = self.plan_relation(j.left, outer, ctes)
        # CROSS JOIN UNNEST(expr-over-left) — correlated array expansion
        inner_rel, u_alias, u_cols = j.right, None, ()
        if isinstance(inner_rel, ast.AliasedRelation):
            u_alias, u_cols = inner_rel.alias, inner_rel.column_aliases
            inner_rel = inner_rel.relation
        if isinstance(inner_rel, ast.Unnest):
            if j.kind not in ("cross", "inner") or j.on is not None or j.using:
                raise AnalysisError(
                    f"{j.kind.upper()} JOIN UNNEST with condition not supported"
                )
            return self.plan_unnest(
                inner_rel, left, outer, ctes,
                alias=u_alias, column_aliases=u_cols,
            )
        if isinstance(inner_rel, ast.SubqueryRelation) and inner_rel.lateral:
            if j.kind in ("cross", "inner") and j.on is None and not j.using:
                return self.plan_lateral(
                    left, inner_rel.query, outer, ctes,
                    alias=u_alias, column_aliases=u_cols,
                )
            # LEFT JOIN LATERAL ... ON cond: fall through to ordinary join
            # planning (works when the subquery is uncorrelated; correlated
            # references fail with column-not-found like before)
        right = self.plan_relation(j.right, outer, ctes)
        fields = left.fields + right.fields
        if j.kind == "cross":
            node = P.JoinNode("cross", left.node, right.node, [])
            return RelationPlan(node, fields)
        criteria = []
        residual: list[Expr] = []
        scope = Scope(fields, outer)
        left_syms = {f.symbol.name for f in left.fields}
        right_syms = {f.symbol.name for f in right.fields}
        conjuncts: list[ast.Node] = []
        if j.on is not None:
            conjuncts = split_conjuncts(j.on)
        for name in j.using:
            lsym = Scope(left.fields).resolve((name,))[0]
            rsym = Scope(right.fields).resolve((name,))[0]
            criteria.append((lsym, rsym))
        an = ExprAnalyzer(scope)
        for c in conjuncts:
            e = an.analyze(c)
            pair = _as_equi_pair(e, left_syms, right_syms)
            if pair is not None:
                criteria.append(pair)
            else:
                residual.append(e)
        if not criteria and j.kind in ("left", "right", "full"):
            raise AnalysisError(
                f"{j.kind.upper()} JOIN requires an equi-join condition"
            )
        node = P.JoinNode(
            j.kind, left.node, right.node, criteria,
            ir.and_(*residual) if residual else None,
        )
        return RelationPlan(node, fields)

    def plan_lateral(
        self, left, q: ast.Query, outer, ctes, alias=None, column_aliases=()
    ) -> RelationPlan:
        """LATERAL (subquery referencing left-relation columns) — the
        correlated-apply relation (reference: sql/tree/Lateral.java +
        the TransformCorrelated* decorrelation rules).  Decorrelates into
        ordinary joins the same way plan_subquery_value does: correlated
        equi-conjuncts become join criteria, aggregates group by them."""
        spec = _subquery_spec(q)
        if spec.distinct:
            raise AnalysisError("LATERAL with SELECT DISTINCT not supported")
        lat_scope = left.scope(outer)

        def _named(i, default):
            return column_aliases[i] if i < len(column_aliases) else default

        # projection-only lateral (no FROM): computed columns over each
        # left row — the common `lateral (select expr as x)` idiom
        if spec.relation is None:
            if spec.group_by or spec.having is not None or spec.where is not None:
                raise AnalysisError(
                    "LATERAL without FROM supports plain SELECT only"
                )
            an = ExprAnalyzer(lat_scope)
            assigns = [(f.symbol, f.symbol.ref()) for f in left.fields]
            new_fields = []
            for i, item in enumerate(spec.items):
                if not isinstance(item, ast.SelectItem):
                    raise AnalysisError(
                        "SELECT * not supported in LATERAL without FROM"
                    )
                e = an.analyze(item.expr)
                name = _named(i, item.alias or _name_hint(item.expr))
                sym = self.alloc.new(name, e.type)
                assigns.append((sym, e))
                new_fields.append(Field(name, sym, alias))
            node = P.ProjectNode(left.node, assigns)
            return RelationPlan(node, left.fields + new_fields)

        agg_calls: list = []
        for item in spec.items:
            if isinstance(item, ast.SelectItem):
                collect_aggregates(item.expr, agg_calls)
        aggregated = bool(agg_calls or spec.group_by or spec.having is not None)

        if q.order_by or q.limit is not None or q.offset is not None:
            # uncorrelated only: plan the whole query (order/limit intact)
            # and cross join; correlated references fail cleanly inside.
            # Silently dropping the ordering/limit is never acceptable.
            if aggregated:
                raise AnalysisError(
                    "LATERAL aggregate with ORDER BY/LIMIT not supported"
                )
            rp, names = self.plan_query(q, outer, ctes)
            fields = [
                Field(_named(i, n), f.symbol, alias)
                for i, (n, f) in enumerate(zip(names, rp.fields))
            ]
            node = P.JoinNode("cross", left.node, rp.node, [])
            return RelationPlan(node, left.fields + fields)

        # plan the lateral FROM, then classify WHERE conjuncts exactly like
        # plan_subquery_value: local filters apply in place, correlated
        # equi-conjuncts become (outer, inner) criteria, the rest residual
        sub = self.plan_relation(spec.relation, lat_scope, ctes)
        sub_scope = sub.scope(lat_scope)
        sub_syms = {f.symbol.name for f in sub.fields}
        crit: list[tuple] = []
        correlated: list[Expr] = []
        if spec.where is not None:
            for c in split_conjuncts(spec.where):
                if _contains_subquery(c):
                    # nested subquery conjunct: applied over the lateral
                    # relation ONLY (outer=None) — a left-column reference
                    # here would otherwise build a filter below the join
                    # over symbols the sub never produces
                    sub = self._apply_where(sub, c, None, ctes)
                    sub_scope = sub.scope(lat_scope)
                    continue
                outer_refs: set = set()
                an = ExprAnalyzer(sub_scope, outer_refs=outer_refs)
                e = an.analyze(c)
                if not outer_refs:
                    sub = RelationPlan(P.FilterNode(sub.node, e), sub.fields)
                    sub_scope = sub.scope(lat_scope)
                    continue
                pair = _as_equi_pair(e, outer_refs, sub_syms)
                if pair is not None:
                    crit.append(pair)
                else:
                    correlated.append(e)

        if aggregated:
            if correlated:
                # residuals reference pre-aggregation inner symbols that the
                # aggregation output no longer exposes
                raise AnalysisError(
                    "correlated LATERAL aggregate supports equi-join "
                    "correlation only"
                )
            inner_keys = [i for _, i in crit]
            spec2 = ast.QuerySpec(
                spec.items, None, None, spec.group_by, spec.having, False
            )
            rp2, names2 = self._plan_aggregation(
                spec2, sub, sub_scope, lat_scope, ctes, extra_keys=inner_keys
            )
            nk = len(inner_keys)
            if crit:
                out_keys = [rp2.fields[i].symbol for i in range(nk)]
                # no GROUP BY: the subquery yields exactly one row per outer
                # row even over an empty group, so unmatched outers survive
                # (LEFT); with a user GROUP BY an empty group yields nothing
                # and the outer row must drop (INNER)
                kind = "inner" if spec.group_by else "left"
                node = P.JoinNode(
                    kind, left.node, rp2.node,
                    [(o, k) for (o, _), k in zip(crit, out_keys)],
                    None,
                )
            else:
                node = P.JoinNode("cross", left.node, rp2.node, [])
            val_fields = [
                Field(_named(i - nk, names2[i]), rp2.fields[i].symbol, alias)
                for i in range(nk, len(rp2.fields))
            ]
            out = RelationPlan(node, left.fields + val_fields)
            if crit and _is_bare_count(spec):
                # count over no matching rows reads NULL off the LEFT JOIN
                # but must be 0 (the classic count bug)
                f0 = val_fields[0]
                fixed = self.alloc.new(f0.name, T.BIGINT)
                assigns = [
                    (f.symbol, f.symbol.ref()) for f in left.fields
                ] + [
                    (
                        fixed,
                        SpecialForm(
                            Form.COALESCE,
                            [f0.symbol.ref(), Literal(0, T.BIGINT)],
                            T.BIGINT,
                        ),
                    )
                ]
                out = RelationPlan(
                    P.ProjectNode(out.node, assigns),
                    left.fields + [Field(f0.name, fixed, alias)],
                )
            return out

        # non-aggregated: correlated equi pairs join, items project over the
        # combined row (they may mix inner and outer columns)
        if crit or correlated:
            node = P.JoinNode(
                "inner", left.node, sub.node, crit,
                ir.and_(*correlated) if correlated else None,
            )
        else:
            node = P.JoinNode("cross", left.node, sub.node, [])
        combined = RelationPlan(node, left.fields + sub.fields)
        an = ExprAnalyzer(combined.scope(outer))
        assigns = [(f.symbol, f.symbol.ref()) for f in left.fields]
        new_fields = []
        i = 0
        for item in spec.items:
            if isinstance(item, ast.Star):
                for f in sub.fields:
                    if item.qualifier and f.alias != item.qualifier[-1]:
                        continue  # t.* expands t's columns only
                    assigns.append((f.symbol, f.symbol.ref()))
                    new_fields.append(Field(_named(i, f.name), f.symbol, alias))
                    i += 1
                continue
            e = an.analyze(item.expr)
            name = _named(i, item.alias or _name_hint(item.expr))
            sym = self.alloc.new(name, e.type)
            assigns.append((sym, e))
            new_fields.append(Field(name, sym, alias))
            i += 1
        return RelationPlan(
            P.ProjectNode(combined.node, assigns), left.fields + new_fields
        )

    # -- SELECT core ---------------------------------------------------------

    def plan_query_spec(self, spec: ast.QuerySpec, outer, ctes):
        # FROM
        if spec.relation is not None:
            rp = self.plan_relation(spec.relation, outer, ctes)
        else:
            rp = RelationPlan(P.ValuesNode([], [[]]), [])
        source_scope = rp.scope(outer)

        # WHERE (with subquery grafting)
        if spec.where is not None:
            rp = self._apply_where(rp, spec.where, outer, ctes)
            source_scope = rp.scope(outer)

        # aggregation?
        agg_calls: list[ast.FunctionCall] = []
        for item in spec.items:
            if isinstance(item, ast.SelectItem):
                collect_aggregates(item.expr, agg_calls)
        if spec.having is not None:
            collect_aggregates(spec.having, agg_calls)
        has_agg = bool(spec.group_by) or bool(agg_calls)

        names: list[str] = []
        if has_agg:
            rp, names = self._plan_aggregation(spec, rp, source_scope, outer, ctes)
        else:
            src_fields = rp.fields
            rp, names = self._plan_select_items(spec, rp, source_scope, outer, ctes)
            if not spec.distinct:
                # ORDER BY may reference source columns that are not output
                # items; DISTINCT forbids that (post-dedupe rows have no
                # source row identity)
                rp.source_fields = src_fields

        if spec.distinct:
            rp = RelationPlan(
                P.AggregationNode(rp.node, [f.symbol for f in rp.fields], []),
                rp.fields,
            )
        return rp, names

    def _plan_select_items(self, spec, rp, scope, outer, ctes):
        assignments = []
        fields = []
        names = []
        graft = _SubqueryGrafter(self, rp, outer, ctes)
        windows = _WindowExtractor(self, scope)
        an = ExprAnalyzer(scope, on_subquery=graft, hook=windows.hook)
        for item in spec.items:
            if isinstance(item, ast.Star):
                for f in rp.fields:
                    if item.qualifier and f.alias != item.qualifier[-1]:
                        continue
                    assignments.append((f.symbol, f.symbol.ref()))
                    fields.append(Field(f.name, f.symbol))
                    names.append(f.name)
                continue
            e = an.analyze(item.expr)
            name = item.alias or _name_hint(item.expr)
            sym = self.alloc.new(name, e.type)
            assignments.append((sym, e))
            fields.append(
                Field(
                    name if item.alias else sym.name,
                    sym,
                    _source_alias(item),
                    _source_column(item),
                    item.expr,
                )
            )
            names.append(name)
        rp = graft.plan  # subqueries may have grown the source plan
        node = windows.attach(rp.node, rp.fields)
        node = P.ProjectNode(node, assignments)
        return RelationPlan(node, fields), names

    @staticmethod
    def _expand_grouping_sets(group_by):
        """Normalize GROUP BY elements into explicit grouping sets
        (reference: QueryPlanner.planGroupingSets / Analyzer grouping-set
        cross product).  Returns None for a plain single-set GROUP BY, else
        the list of sets as tuples of AST exprs (cross product across
        elements, per the SQL spec)."""
        import itertools

        if not any(isinstance(g, ast.GroupingElement) for g in group_by):
            return None
        per_element = []
        for g in group_by:
            if not isinstance(g, ast.GroupingElement):
                per_element.append([(g,)])
            elif g.kind == "rollup":
                per_element.append(
                    [tuple(g.sets[:i]) for i in range(len(g.sets), -1, -1)]
                )
            elif g.kind == "cube":
                exprs = list(g.sets)
                subs = []
                for r in range(len(exprs), -1, -1):
                    subs.extend(itertools.combinations(exprs, r))
                per_element.append([tuple(s) for s in subs])
            else:  # explicit GROUPING SETS
                per_element.append([tuple(s) for s in g.sets])
        return [
            tuple(e for part in combo for e in part)
            for combo in itertools.product(*per_element)
        ]

    def _plan_aggregation(self, spec, rp, source_scope, outer, ctes, extra_keys=()):
        """`extra_keys`: source symbols injected as group keys and kept in the
        output (used by subquery decorrelation)."""
        alloc = self.alloc
        pre_assign: list = []  # [(Symbol, Expr)] inputs to the aggregation
        pre_map: dict = {}  # ir key -> Symbol

        def pre_symbol(e: Expr, hint: str) -> P.Symbol:
            k = e.key()
            if k in pre_map:
                return pre_map[k]
            if isinstance(e, SymbolRef):
                sym = P.Symbol(e.name, e.type)
            else:
                sym = alloc.new(hint, e.type)
            pre_map[k] = sym
            pre_assign.append((sym, e))
            return sym

        graft = _SubqueryGrafter(self, rp, outer, ctes)
        src_an = ExprAnalyzer(source_scope, on_subquery=graft)

        # grouping sets (ROLLUP/CUBE/GROUPING SETS) normalize to an explicit
        # set list; plain GROUP BY keeps gsets=None
        gsets_ast = self._expand_grouping_sets(spec.group_by)

        def _resolve_ordinal(g):
            if isinstance(g, ast.NumberLiteral):
                return spec.items[int(g.text) - 1].expr
            return g

        # group-by expressions (ordinals allowed): the UNION of all keys
        # across sets, in first-appearance order
        group_irs: list[Expr] = []
        group_syms: list[P.Symbol] = []
        group_keys: dict = {}
        for ksym in extra_keys:
            e = ksym.ref()
            if e.key() in group_keys:
                continue
            sym = pre_symbol(e, ksym.name)
            group_syms.append(sym)
            group_keys[e.key()] = sym
        flat_exprs = (
            [g for g in spec.group_by]
            if gsets_ast is None
            else [e for s in gsets_ast for e in s]
        )
        for g in flat_exprs:
            g = _resolve_ordinal(g)
            e = src_an.analyze(g)
            if e.key() in group_keys:
                continue
            sym = pre_symbol(e, _name_hint(g))
            group_irs.append(e)
            group_syms.append(sym)
            group_keys[e.key()] = sym
        # per-set membership by analyzed expr key; decorrelation extra_keys
        # group in EVERY set
        gid_sym = None
        set_keys = None
        if gsets_ast is not None:
            gid_sym = alloc.new("groupid", T.BIGINT)
            extra = {k.ref().key() for k in extra_keys}
            set_keys = [
                extra | {src_an.analyze(_resolve_ordinal(e)).key() for e in s}
                for s in gsets_ast
            ]

        # aggregates discovered lazily while translating post-agg expressions
        aggregations: list = []  # [(Symbol, P.Aggregation)]
        agg_map: dict = {}

        def agg_symbol(fc: ast.FunctionCall) -> P.Symbol:
            filter_ir = None
            filter_key = None
            if fc.filter is not None:
                filter_sym = pre_symbol(
                    src_an.analyze(fc.filter), "agg_filter"
                )
                filter_ir = filter_sym.ref()
                filter_key = filter_ir.key()
            distinct = fc.distinct
            param = None
            fn_args = list(fc.args)
            sql_name = fc.name
            if sql_name == "approx_distinct":
                # reference role: ApproximateCountDistinctAggregation.
                # Global form: real HyperLogLog (bounded, mergeable per-chip
                # registers).  Grouped form: exact DISTINCT count rewrite —
                # per-group register matrices are not materialized.
                fn_args = fn_args[:1]  # drop max-standard-error argument
                if spec.group_by or extra_keys:
                    sql_name, distinct = "count", True
            if fc.is_star and sql_name == "count":
                key = ("count_star", (), False, filter_key)
                fname, arg_syms, arg_t = "count_star", [], None
                arg_irs = []
            else:
                fname = AGG_FUNCS[sql_name]
                if fname == "percentile":
                    if (
                        sql_name == "approx_percentile"
                        and not (spec.group_by or extra_keys)
                    ):
                        # global form: mergeable log-bucket sketch (bounded
                        # state, reference: qdigest); grouped form stays the
                        # exact sort-based percentile
                        fname = "approx_percentile"
                    if len(fn_args) != 2:
                        # weighted / accuracy signatures would silently give
                        # wrong numbers — reject anything but (value, frac)
                        raise AnalysisError(
                            "approx_percentile supports exactly "
                            "(value, percentile)"
                        )
                    p_ir = src_an.analyze(fn_args[-1])
                    from trino_tpu.expr.constant_folding import try_fold

                    p_ir = try_fold(p_ir)
                    if not isinstance(p_ir, Literal):
                        raise AnalysisError(
                            "approx_percentile fraction must be a literal"
                        )
                    param = float(p_ir.value)
                    fn_args = fn_args[:1]
                if fc.within_group and fname not in ("array_agg", "listagg"):
                    raise AnalysisError(
                        f"ORDER BY in arguments is not supported for {fname}"
                    )
                if fname == "array_agg" and fc.within_group:
                    # array_agg(x ORDER BY k): the order key rides as a
                    # second projected argument; param = (asc, nulls_first)
                    if len(fc.within_group) > 1:
                        raise AnalysisError(
                            "array_agg supports a single ORDER BY key"
                        )
                    if distinct:
                        raise AnalysisError(
                            "array_agg does not support DISTINCT with ORDER BY"
                        )
                    order = fc.within_group[0]
                    param = (
                        order.ascending,
                        bool(order.nulls_first)
                        if order.nulls_first is not None
                        else False,
                    )
                    fn_args = fn_args[:1] + [order.expr]
                if fname in ("min_by", "max_by") and len(fn_args) == 3:
                    # N-form: min_by/max_by(value, key, n) returns the array
                    # of values at the n extreme keys (reference:
                    # MinMaxByNAggregation); n folds to the AggSpec param
                    from trino_tpu.expr.constant_folding import try_fold

                    n_ir = try_fold(src_an.analyze(fn_args[2]))
                    if (
                        not isinstance(n_ir, Literal)
                        or not isinstance(n_ir.value, int)
                        or isinstance(n_ir.value, bool)
                        or n_ir.value < 1
                    ):
                        raise AnalysisError(
                            f"{fname} n must be a positive integer literal"
                        )
                    if n_ir.value > 10_000:
                        # dense [groups, n] state; the reference caps n at
                        # 10000 (MinMaxByNAggregation) for the same reason
                        raise AnalysisError(
                            f"{fname} n must not exceed 10000"
                        )
                    param = n_ir.value
                    fn_args = fn_args[:2]
                if fname == "listagg":
                    # listagg(value [, separator]) [WITHIN GROUP (ORDER BY k)]
                    # — separator folds to the AggSpec param; the first order
                    # key rides as a second projected argument
                    sep = ""  # SQL:2016 default: empty separator
                    if len(fn_args) > 1:
                        from trino_tpu.expr.constant_folding import try_fold

                        s_ir = try_fold(src_an.analyze(fn_args[1]))
                        if not isinstance(s_ir, Literal) or not isinstance(
                            s_ir.value, str
                        ):
                            raise AnalysisError(
                                "listagg separator must be a string literal"
                            )
                        sep = s_ir.value
                    if len(fc.within_group) > 1:
                        raise AnalysisError(
                            "listagg supports a single WITHIN GROUP order key"
                        )
                    order = fc.within_group[0] if fc.within_group else None
                    # param carries (separator, ascending, nulls_first)
                    param = (
                        sep,
                        order.ascending if order is not None else True,
                        bool(order.nulls_first) if order is not None and order.nulls_first is not None else False,
                    )
                    fn_args = fn_args[:1] + ([order.expr] if order is not None else [])
                arg_irs = [src_an.analyze(a) for a in fn_args]
                key = (
                    fname,
                    tuple(a.key() for a in arg_irs),
                    distinct,
                    filter_key,
                    param,
                )
                arg_syms = [
                    pre_symbol(a, _name_hint(fn_args[i]))
                    for i, a in enumerate(arg_irs)
                ]
                arg_t = arg_irs[0].type if arg_irs else None
            if key in agg_map:
                return agg_map[key]
            arg_t2 = arg_irs[1].type if len(arg_irs) > 1 else None
            out_t = agg_result_type(fname, arg_t, arg_t2)
            if fname in ("min_by", "max_by") and param is not None:
                out_t = T.ArrayType(arg_t)  # the N-form collects an array
            sym = alloc.new(fc.name, out_t)
            aggregations.append(
                (
                    sym,
                    P.Aggregation(
                        fname,
                        [s.ref() for s in arg_syms],
                        distinct,
                        filter_ir,
                        param,
                    ),
                )
            )
            agg_map[key] = sym
            return sym

        def grouping_ir(node: ast.FunctionCall) -> Expr:
            """GROUPING(e1..em): bitmask of which args are NOT grouped in
            this row's grouping set, decoded from the group-id column
            (reference: sql/analyzer — GroupingOperationRewriter)."""
            if set_keys is None:
                # single grouping set: every argument is grouped
                return Literal(0, T.BIGINT)
            arg_keys = [src_an.analyze(a).key() for a in node.args]
            masks = []
            for sk in set_keys:
                bits = 0
                for j, ak in enumerate(arg_keys):
                    if ak not in sk:
                        bits |= 1 << (len(arg_keys) - 1 - j)
                masks.append(bits)
            if len(set(masks)) == 1:
                return Literal(masks[0], T.BIGINT)
            args: list[Expr] = []
            for k, bits in enumerate(masks[:-1]):
                args.append(
                    ir.comparison("=", gid_sym.ref(), Literal(k, T.BIGINT))
                )
                args.append(Literal(bits, T.BIGINT))
            args.append(Literal(masks[-1], T.BIGINT))
            return SpecialForm(Form.CASE, args, T.BIGINT)

        def post_hook(node: ast.Node, _an) -> Optional[Expr]:
            if isinstance(node, ast.FunctionCall) and node.name == "grouping":
                return grouping_ir(node)
            if (
                isinstance(node, ast.FunctionCall)
                and node.name in REWRITTEN_AGGS
                and node.window is None
            ):
                if node.name == "count_if":
                    # reference: CountIfAggregation = count(*) FILTER (cond)
                    if node.distinct:
                        raise AnalysisError("count_if does not support DISTINCT")
                    cond = node.args[0]
                    if node.filter is not None:
                        cond = ast.BinaryOp("and", cond, node.filter)
                    inner = ast.FunctionCall(
                        "count", (), is_star=True, filter=cond
                    )
                    return agg_symbol(inner).ref()
                # geometric_mean (reference: GeometricMeanAggregations) —
                # exp of the mean of logs; planned as that composition
                inner = ast.FunctionCall(
                    "avg",
                    (ast.FunctionCall("ln", tuple(node.args)),),
                    distinct=node.distinct,
                    filter=node.filter,
                )
                return Call("exp", [agg_symbol(inner).ref()], T.DOUBLE)
            if isinstance(node, ast.FunctionCall) and node.window is None and (
                node.name in AGG_FUNCS or (node.is_star and node.name == "count")
            ):
                return agg_symbol(node).ref()
            # match against group-by expressions.  TypeError covers
            # speculative analysis of expressions containing functions the
            # scalar registry doesn't know (e.g. grouping() nested inside
            # arithmetic — resolved by this hook on recursion, not here).
            try:
                e = src_an.analyze(node)
            except (AnalysisError, TypeError):
                return None
            sym = group_keys.get(e.key())
            if sym is not None:
                return sym.ref()
            if isinstance(node, ast.Identifier):
                raise AnalysisError(
                    f"column {'.'.join(node.parts)} must appear in GROUP BY "
                    "or be used in an aggregate"
                )
            return None

        # translate select items (this fills pre_assign/aggregations)
        post_assignments = []
        post_fields = []
        names = []
        # injected decorrelation keys lead the output so callers can find them
        for ksym in extra_keys:
            gsym = group_keys[ksym.ref().key()]
            post_assignments.append((gsym, gsym.ref()))
            post_fields.append(Field(gsym.name, gsym))
            names.append(gsym.name)
        # windows over the aggregation's output (planWindowFunctions runs
        # after aggregation planning in the reference's QueryPlanner)
        wx = _WindowExtractor(self, source_scope, an_hook=post_hook)

        def item_hook(node: ast.Node, an) -> Optional[Expr]:
            got = wx.hook(node, an)
            if got is not None:
                return got
            return post_hook(node, an)

        for item in spec.items:
            if isinstance(item, ast.Star):
                raise AnalysisError("SELECT * not allowed with GROUP BY")
            post_an = ExprAnalyzer(source_scope, hook=item_hook)
            e = post_an.analyze(item.expr)
            name = item.alias or _name_hint(item.expr)
            sym = alloc.new(name, e.type)
            post_assignments.append((sym, e))
            post_fields.append(
                Field(
                    name if item.alias else sym.name,
                    sym,
                    _source_alias(item),
                    _source_column(item),
                    item.expr,
                )
            )
            names.append(name)

        having_ir = None
        having_subqueries = []
        if spec.having is not None:
            for conj in split_conjuncts(spec.having):
                if _contains_subquery(conj):
                    having_subqueries.append(conj)
                else:
                    post_an = ExprAnalyzer(source_scope, hook=post_hook)
                    e = post_an.analyze(conj)
                    having_ir = ir.and_(having_ir, e) if having_ir is not None else e

        # assemble: graft plan -> pre-project -> aggregate -> having -> project
        src_node = graft.plan.node
        # keep any source symbols referenced by pre_assign
        pre_node = P.ProjectNode(src_node, pre_assign)
        if gsets_ast is None:
            agg_node = P.AggregationNode(pre_node, group_syms, aggregations)
        else:
            # GroupIdNode analog (reference: sql/planner/plan/GroupIdNode
            # .java:40): K-way input duplication — one UNION ALL branch per
            # grouping set, non-member keys nulled, a group-id literal
            # appended — then ONE aggregation over (keys..., groupid).
            # Static-shape friendly: K is a plan constant.
            pre_syms = [s for s, _ in pre_assign]
            sym_to_key = {sym.name: k for k, sym in group_keys.items()}
            agg_syms = [s for s, _ in aggregations]
            # the () grouping set must yield its row even over EMPTY input
            # (a global aggregation's semantics) — it cannot ride the keyed
            # aggregation, which yields no groups for no rows
            empty_idx = {
                k for k, s in enumerate(gsets_ast) if not s and not extra_keys
            }
            # each grouping-set branch owns its OWN instance of the
            # pre-projected input (one shared instance in K tree positions
            # breaks the duplicate-node sanity rule); the first consumer
            # takes the original, later ones take copies
            _pre_used = [False]

            def own_pre():
                if _pre_used[0]:
                    return P.copy_tree(pre_node)
                _pre_used[0] = True
                return pre_node

            branches = []
            branch_syms = []
            for k, sk in enumerate(set_keys):
                if k in empty_idx:
                    continue
                assigns = []
                bsyms = []
                for s in pre_syms:
                    bs = alloc.new(s.name, s.type)
                    if s.name in sym_to_key and sym_to_key[s.name] not in sk:
                        assigns.append((bs, Literal(None, s.type)))
                    else:
                        assigns.append((bs, s.ref()))
                    bsyms.append(bs)
                bgid = alloc.new("groupid", T.BIGINT)
                assigns.append((bgid, Literal(k, T.BIGINT)))
                bsyms.append(bgid)
                branches.append(P.ProjectNode(own_pre(), assigns))
                branch_syms.append(bsyms)
            main = None
            if branches:
                union_node = P.UnionNode(
                    branches, pre_syms + [gid_sym], branch_syms
                )
                main = P.AggregationNode(
                    union_node, group_syms + [gid_sym], aggregations
                )
            pads = []
            for k in sorted(empty_idx):
                gaggs = [
                    (alloc.new(s.name, s.type), spec) for s, spec in aggregations
                ]
                gnode = P.AggregationNode(own_pre(), [], gaggs)
                passigns = []
                psyms = []
                for s in group_syms:
                    ns = alloc.new(s.name, s.type)
                    passigns.append((ns, Literal(None, s.type)))
                    psyms.append(ns)
                ngid = alloc.new("groupid", T.BIGINT)
                passigns.append((ngid, Literal(k, T.BIGINT)))
                psyms.append(ngid)
                for gs, _spec in gaggs:
                    ns = alloc.new(gs.name, gs.type)
                    passigns.append((ns, gs.ref()))
                    psyms.append(ns)
                pads.append((P.ProjectNode(gnode, passigns), psyms))
            canonical = group_syms + [gid_sym] + agg_syms
            if main is not None and not pads:
                agg_node = main
            else:
                sources = ([main] if main is not None else []) + [
                    p for p, _ in pads
                ]
                srcsyms = ([canonical] if main is not None else []) + [
                    ps for _, ps in pads
                ]
                agg_node = P.UnionNode(sources, canonical, srcsyms)
        cur = RelationPlan(
            agg_node,
            [Field(s.name, s) for s in agg_node.outputs],
        )
        if having_ir is not None:
            cur = RelationPlan(P.FilterNode(cur.node, having_ir), cur.fields)
        for conj in having_subqueries:
            cur = self._apply_conjunct_with_subquery(
                cur, conj, outer, ctes,
                analyzer_factory=lambda g: ExprAnalyzer(
                    source_scope, hook=post_hook, on_subquery=g
                ),
            )
        wnode = wx.attach(cur.node, cur.fields)
        node = P.ProjectNode(wnode, post_assignments)
        return RelationPlan(node, post_fields), names

    # -- WHERE + subqueries --------------------------------------------------

    def _apply_where(self, rp, where: ast.Node, outer, ctes) -> RelationPlan:
        # plain conjuncts first: they form the equi-join edges cross-join
        # elimination needs, and a subquery graft applied over the raw comma
        # cross tree would otherwise bury those edges under its own joins
        # (q30-style plans explode into genuine cross products without this)
        plain = []
        with_subquery = []
        for conj in split_conjuncts(where):
            (with_subquery if _contains_subquery(conj) else plain).append(conj)
        for conj in plain:
            an = ExprAnalyzer(rp.scope(outer))
            rp = RelationPlan(P.FilterNode(rp.node, an.analyze(conj)), rp.fields)
        for conj in with_subquery:
            rp = self._apply_conjunct_with_subquery(rp, conj, outer, ctes)
        return rp

    def _apply_conjunct_with_subquery(
        self, rp, conj: ast.Node, outer, ctes, analyzer_factory=None
    ) -> RelationPlan:
        graft = _SubqueryGrafter(self, rp, outer, ctes)
        if analyzer_factory is not None:
            an = analyzer_factory(graft)
        else:
            an = ExprAnalyzer(rp.scope(outer), on_subquery=graft)
        e = an.analyze(conj)
        out = graft.plan
        return RelationPlan(P.FilterNode(out.node, e), out.fields)

    # -- subquery grafting ---------------------------------------------------

    def plan_subquery_value(self, rp, q: ast.Query, outer_scope, ctes, kind: str,
                            negated: bool = False, in_value: Optional[Expr] = None):
        """Attach a subquery to `rp`; returns (new RelationPlan, value Expr).

        kind: 'scalar' | 'exists' | 'in'
        """
        spec = _subquery_spec(q)
        sub_outer = outer_scope  # subquery sees the enclosing row scope
        # plan FROM
        if spec.relation is None:
            raise AnalysisError("subquery without FROM not supported")
        sub = self.plan_relation(spec.relation, sub_outer, ctes)
        # classify conjuncts
        plain: list[ast.Node] = []
        correlated: list[Expr] = []
        crit: list[tuple] = []  # (outer Symbol, inner Symbol)
        sub_scope = sub.scope(sub_outer)
        sub_syms = {f.symbol.name for f in sub.fields}
        if spec.where is not None:
            for c in split_conjuncts(spec.where):
                if _contains_subquery(c):
                    plain.append(c)  # nested subquery: recurse via _apply_where
                    continue
                outer_refs: set = set()
                an = ExprAnalyzer(sub_scope, outer_refs=outer_refs)
                e = an.analyze(c)
                if not outer_refs:
                    sub = RelationPlan(P.FilterNode(sub.node, e), sub.fields)
                    sub_scope = sub.scope(sub_outer)
                    continue
                pair = _as_equi_pair(e, outer_refs, sub_syms)
                if pair is not None:
                    crit.append(pair)
                    continue
                # correlation buried in a disjunction: factor out equi
                # conjuncts common to EVERY disjunct (q41 shape:
                # `(m = i1.m and A) or (m = i1.m and B)` ->
                # crit gets (m, i1.m), predicate becomes `A or B`)
                factored = _factor_common_equi(e, outer_refs, sub_syms)
                if factored is not None:
                    pairs, rest = factored
                    crit.extend(pairs)
                    if rest is not None:
                        rest_refs: set = set()
                        _collect_ref_names(rest, rest_refs)
                        if rest_refs <= sub_syms:
                            sub = RelationPlan(
                                P.FilterNode(sub.node, rest), sub.fields
                            )
                            sub_scope = sub.scope(sub_outer)
                        else:
                            correlated.append(rest)
                    continue
                correlated.append(e)
        for c in plain:
            sub = self._apply_where(sub, c, sub_outer, ctes)
        # ---- EXISTS / IN ----------------------------------------------------
        if kind == "exists":
            mark = self.alloc.new("exists", T.BOOLEAN)
            if not crit and not correlated:
                # uncorrelated EXISTS: one global count over the subquery,
                # cross-joined (reference: TransformUncorrelatedSubqueryToJoin)
                cnt = self.alloc.new("cnt", T.BIGINT)
                agg = P.AggregationNode(
                    sub.node, [], [(cnt, P.Aggregation("count_star", []))]
                )
                node = P.JoinNode("cross", rp.node, agg, [])
                out = RelationPlan(node, rp.fields + [Field(cnt.name, cnt)])
                val = ir.comparison(">", cnt.ref(), Literal(0, T.BIGINT))
                return out, (ir.not_(val) if negated else val)
            if not crit:
                raise AnalysisError(
                    "correlated EXISTS without an equi-join predicate "
                    "not supported yet"
                )
            (osym, isym), extra = crit[0], crit[1:]
            filt = None
            parts = correlated + [
                ir.comparison("=", o.ref(), i.ref()) for o, i in extra
            ]
            if parts:
                filt = ir.and_(*parts)
            node = P.SemiJoinNode(
                rp.node, sub.node, osym, isym, mark, filt, null_aware=False
            )
            out = RelationPlan(node, rp.fields + [Field(mark.name, mark)])
            val = mark.ref()
            return out, (ir.not_(val) if negated else val)
        if kind == "in":
            # value IN (select col ...): inner value column from select items
            sub_proj, names = self._plan_select_items(spec, sub, sub_scope, sub_outer, ctes)
            if len(sub_proj.fields) != 1:
                raise AnalysisError("IN subquery must return one column")
            item_aggs: list = []
            if spec.items and isinstance(spec.items[0], ast.SelectItem):
                collect_aggregates(spec.items[0].expr, item_aggs)
            if spec.group_by or item_aggs or spec.having is not None:
                # grouped IN subquery (Q18): plan fully then semi join
                sub_full, _ = self.plan_query_spec(spec, sub_outer, ctes)
                inner_sym = sub_full.fields[0].symbol
                sub_node = sub_full.node
            elif crit or correlated:
                # correlated IN: keep the correlation's inner symbols in the
                # filtering side so the semi join's filter can see them
                item = spec.items[0]
                if len(spec.items) != 1 or not isinstance(item, ast.SelectItem):
                    raise AnalysisError("IN subquery must return one column")
                val_e = ExprAnalyzer(sub_scope).analyze(item.expr)
                if isinstance(val_e, SymbolRef):
                    inner_sym = P.Symbol(val_e.name, val_e.type)
                else:
                    inner_sym = self.alloc.new("in_inner", val_e.type)
                needed: dict = {}
                for _, isym in crit:
                    needed[isym.name] = isym
                corr_names: set = set()
                for e in correlated:
                    _collect_ref_names(e, corr_names)
                for f in sub.fields:
                    if f.symbol.name in corr_names:
                        needed.setdefault(f.symbol.name, f.symbol)
                assigns = [(inner_sym, val_e)] + [
                    (sym, sym.ref())
                    for name, sym in needed.items()
                    if name != inner_sym.name
                ]
                sub_node = P.ProjectNode(sub.node, assigns)
            else:
                inner_sym = sub_proj.fields[0].symbol
                sub_node = sub_proj.node
            mark = self.alloc.new("in_mark", T.BOOLEAN)
            if (crit or correlated) and (
                spec.group_by or item_aggs or spec.having is not None
            ):
                raise AnalysisError(
                    "correlated grouped IN subquery not supported yet"
                )
            assert in_value is not None
            if isinstance(in_value, SymbolRef):
                src_sym = P.Symbol(in_value.name, in_value.type)
                src_node = rp.node
                out_fields = rp.fields
            else:
                src_sym = self.alloc.new("in_value", in_value.type)
                src_node = P.ProjectNode(
                    rp.node,
                    [(f.symbol, f.symbol.ref()) for f in rp.fields]
                    + [(src_sym, in_value)],
                )
                out_fields = rp.fields + [Field(src_sym.name, src_sym)]
            # correlated IN: the correlation predicates become the semi
            # join's extra filter over (source ++ filtering) symbols
            # (reference: TransformCorrelatedInPredicateToJoin)
            filt = None
            parts = correlated + [
                ir.comparison("=", o.ref(), i.ref()) for o, i in crit
            ]
            if parts:
                filt = ir.and_(*parts)
            node = P.SemiJoinNode(
                src_node, sub_node, src_sym, inner_sym, mark, filt
            )
            out = RelationPlan(node, out_fields + [Field(mark.name, mark)])
            val = mark.ref()
            return out, (ir.not_(val) if negated else val)
        # ---- scalar ---------------------------------------------------------
        assert kind == "scalar"
        agg_calls: list = []
        for item in spec.items:
            if isinstance(item, ast.SelectItem):
                collect_aggregates(item.expr, agg_calls)
        if not agg_calls and not spec.group_by:
            # non-aggregated scalar subquery: single row enforced
            if crit or correlated:
                raise AnalysisError(
                    "correlated non-aggregated scalar subquery not supported"
                )
            sub_proj, _ = self._plan_select_items(spec, sub, sub_scope, sub_outer, ctes)
            sub_node = sub_proj.node
            if spec.distinct:
                # SELECT DISTINCT x: dedupe before the single-row check
                sub_node = P.AggregationNode(
                    sub_node, [f.symbol for f in sub_proj.fields], []
                )
            single = P.EnforceSingleRowNode(sub_node)
            node = P.JoinNode("cross", rp.node, single, [])
            out = RelationPlan(node, rp.fields + sub_proj.fields)
            return out, sub_proj.fields[0].symbol.ref()
        # aggregated scalar subquery: group by correlation keys, LEFT JOIN
        inner_keys = [i for _, i in crit]
        spec2 = ast.QuerySpec(
            spec.items, None, None, spec.group_by, spec.having, False
        )
        rp2, names2 = self._plan_aggregation(
            spec2, sub, sub_scope, sub_outer, ctes, extra_keys=inner_keys
        )
        if crit:
            # join against the *output* key symbols of the grouped subquery
            out_keys = [rp2.fields[i].symbol for i in range(len(inner_keys))]
            node = P.JoinNode(
                "left",
                rp.node,
                rp2.node,
                [(o, k) for (o, _), k in zip(crit, out_keys)],
                ir.and_(*correlated) if correlated else None,
            )
            out = RelationPlan(node, rp.fields + rp2.fields)
            value_sym = rp2.fields[len(inner_keys)].symbol
            val: Expr = value_sym.ref()
            # count over no matching rows must be 0, but the LEFT JOIN yields
            # NULL for unmatched outer rows — coalesce when the subquery's
            # value is exactly a count aggregate (the classic count bug)
            if _is_bare_count(spec):
                val = SpecialForm(
                    Form.COALESCE, [val, Literal(0, T.BIGINT)], T.BIGINT
                )
            return out, val
        # uncorrelated aggregated scalar: global agg -> single row cross join
        if correlated:
            # refusing is mandatory: a dropped correlation silently counts
            # the WHOLE inner relation per outer row (wrong results)
            raise AnalysisError(
                "correlated aggregated scalar subquery without an equi-join "
                "predicate not supported"
            )
        node = P.JoinNode("cross", rp.node, rp2.node, [])
        out = RelationPlan(node, rp.fields + rp2.fields)
        return out, rp2.fields[0].symbol.ref()


def _collect_ref_names(e: Expr, out: set) -> None:
    """Names of every SymbolRef inside `e`."""
    from trino_tpu.expr.ir import visit

    def fn(x):
        if isinstance(x, SymbolRef):
            out.add(x.name)
        return x

    visit(e, fn)


def _is_bare_count(spec: ast.QuerySpec) -> bool:
    if len(spec.items) != 1 or not isinstance(spec.items[0], ast.SelectItem):
        return False
    e = spec.items[0].expr
    return isinstance(e, ast.FunctionCall) and e.name == "count"


def _subquery_spec(q: ast.Query) -> ast.QuerySpec:
    body = q.body
    if isinstance(body, ast.QuerySpec):
        return body
    raise AnalysisError("unsupported subquery shape")


#: window functions and their result-type rules (reference: the
#: operator/window/* function registry)
_WINDOW_RANK = {"row_number", "rank", "dense_rank", "ntile"}
_WINDOW_DOUBLE = {"percent_rank", "cume_dist"}
_WINDOW_VALUE = {"lag", "lead", "first_value", "last_value", "nth_value"}


class _WindowExtractor:
    """Collects OVER() calls during select-item translation and attaches a
    WindowNode below the final projection (reference role: the window planning
    in QueryPlanner.planWindowFunctions)."""

    def __init__(self, planner: "LogicalPlanner", scope: Scope, an_hook=None):
        self.planner = planner
        self.scope = scope
        #: analyzer hook for window args/partition/order — the aggregation
        #: planner passes its post-agg translation hook so windows OVER
        #: aggregates (`sum(sum(x)) over (partition by k)`, the reference's
        #: planWindowFunctions-after-aggregation ordering) resolve inner
        #: aggregates and group keys to their computed symbols
        self.an_hook = an_hook
        self.pre_assign: list = []  # [(Symbol, Expr)] computed inputs
        self.pre_map: dict = {}
        self.functions: list = []  # [(out Symbol, partition syms, order, fn)]

    def hook(self, node: ast.Node, _an) -> Optional[Expr]:
        if not (isinstance(node, ast.FunctionCall) and node.window is not None):
            return None
        return self._plan_call(node).ref()

    def _pre_symbol(self, e: Expr, hint: str) -> P.Symbol:
        k = e.key()
        if k in self.pre_map:
            return self.pre_map[k]
        if isinstance(e, SymbolRef):
            sym = P.Symbol(e.name, e.type)
        else:
            sym = self.planner.alloc.new(hint, e.type)
        self.pre_map[k] = sym
        self.pre_assign.append((sym, e))
        return sym

    def _plan_call(self, fc: ast.FunctionCall) -> P.Symbol:
        an = ExprAnalyzer(self.scope, hook=self.an_hook)
        w = fc.window
        if getattr(w, "ref", None) is not None:
            raise AnalysisError(f"window '{w.ref}' is not defined")
        if fc.ignore_nulls and fc.name not in (
            "lag", "lead", "first_value", "last_value", "nth_value"
        ):
            raise AnalysisError(
                f"IGNORE NULLS is not valid for {fc.name}"
            )
        part = [
            self._pre_symbol(an.analyze(p), _name_hint(p)) for p in w.partition_by
        ]
        order = []
        for si in w.order_by:
            e = an.analyze(si.expr)
            nf = si.nulls_first
            if nf is None:
                nf = not si.ascending  # NULLS LAST asc / FIRST desc default
            order.append(
                (self._pre_symbol(e, _name_hint(si.expr)), si.ascending, nf)
            )
        name = fc.name
        arg_syms: list = []
        offset, n_buckets, default_sym = 1, 1, None
        if name in _WINDOW_RANK or name in _WINDOW_DOUBLE:
            if name == "ntile":
                lit = an.analyze(fc.args[0])
                if not isinstance(lit, Literal):
                    raise AnalysisError("ntile bucket count must be a literal")
                n_buckets = int(lit.value)
            out_t = T.DOUBLE if name in _WINDOW_DOUBLE else T.BIGINT
        elif name in _WINDOW_VALUE:
            if name == "nth_value" and len(fc.args) != 2:
                raise AnalysisError("nth_value requires (value, n)")
            if not fc.args:
                raise AnalysisError(f"{name} requires an argument")
            arg = an.analyze(fc.args[0])
            arg_syms = [self._pre_symbol(arg, _name_hint(fc.args[0]))]
            out_t = arg.type
            if name in ("lag", "lead"):
                if len(fc.args) > 1:
                    off = an.analyze(fc.args[1])
                    if not isinstance(off, Literal):
                        raise AnalysisError("lag/lead offset must be a literal")
                    offset = int(off.value)
                if len(fc.args) > 2:
                    default_sym = self._pre_symbol(
                        an.analyze(fc.args[2]), "default"
                    )
            if name == "nth_value":
                off = an.analyze(fc.args[1])
                if not isinstance(off, Literal) or not isinstance(
                    off.value, int
                ):
                    raise AnalysisError(
                        "nth_value n must be an integer literal"
                    )
                offset = off.value
                if offset < 1:
                    raise AnalysisError("nth_value n must be positive")
        elif name in AGG_FUNCS or (fc.is_star and name == "count"):
            if fc.distinct:
                raise AnalysisError(
                    "DISTINCT aggregates are not supported as window functions"
                )
            if fc.is_star:
                name, out_t = "count_star", T.BIGINT
            else:
                if AGG_FUNCS.get(name) not in (
                    "count", "sum", "avg", "min", "max",
                ) or name == "approx_distinct":
                    raise AnalysisError(
                        f"{name} is not supported as a window function"
                    )
                arg = an.analyze(fc.args[0])
                arg_syms = [self._pre_symbol(arg, _name_hint(fc.args[0]))]
                out_t = agg_result_type(AGG_FUNCS[name], arg.type)
                name = AGG_FUNCS[name]
        else:
            raise AnalysisError(f"unknown window function {name}")
        frame, start_off, end_off = _resolve_frame(w.frame, bool(order))
        fn = P.WindowFunction(
            name,
            [s.ref() for s in arg_syms],
            frame=frame,
            offset=offset,
            n_buckets_expr=n_buckets,
            default=None if default_sym is None else default_sym.ref(),
            start_off=start_off,
            end_off=end_off,
            ignore_nulls=fc.ignore_nulls,
        )
        out = self.planner.alloc.new(fc.name, out_t)
        self.functions.append((out, part, order, fn))
        return out

    def attach(self, node: P.PlanNode, fields) -> P.PlanNode:
        if not self.functions:
            return node
        # pre-project: every source field plus computed window inputs
        seen = {f.symbol.name for f in fields}
        assigns = [(f.symbol, f.symbol.ref()) for f in fields]
        for sym, e in self.pre_assign:
            if sym.name not in seen:
                assigns.append((sym, e))
                seen.add(sym.name)
        node = P.ProjectNode(node, assigns)
        # one WindowNode per distinct (partition, order) spec
        by_spec: dict = {}
        for out, part, order, fn in self.functions:
            key = (
                tuple(s.name for s in part),
                tuple((s.name, a, nf) for s, a, nf in order),
            )
            by_spec.setdefault(key, (part, order, []))[2].append((out, fn))
        for part, order, fns in by_spec.values():
            node = P.WindowNode(node, part, order, fns)
        return node


class _SubqueryGrafter:
    """on_subquery callback: plans subquery expressions against the current
    relation plan, growing it via joins (SubqueryPlanner's apply mechanism)."""

    def __init__(self, planner: LogicalPlanner, rp: RelationPlan, outer, ctes):
        self.planner = planner
        self.plan = rp
        self.outer = outer
        self.ctes = ctes

    def __call__(self, node: ast.Node, an: ExprAnalyzer) -> Expr:
        scope = self.plan.scope(self.outer)
        if isinstance(node, ast.Exists):
            self.plan, val = self.planner.plan_subquery_value(
                self.plan, node.query, scope, self.ctes, "exists", node.negated
            )
            return val
        if isinstance(node, ast.InSubquery):
            value_ir = ExprAnalyzer(scope).analyze(node.value)
            self.plan, val = self.planner.plan_subquery_value(
                self.plan, node.query, scope, self.ctes, "in", node.negated,
                in_value=value_ir,
            )
            return val
        if isinstance(node, ast.ScalarSubquery):
            self.plan, val = self.planner.plan_subquery_value(
                self.plan, node.query, scope, self.ctes, "scalar"
            )
            return val
        raise AnalysisError(f"unsupported subquery node {type(node).__name__}")


def _contains_subquery(node: ast.Node) -> bool:
    if isinstance(node, (ast.Exists, ast.InSubquery, ast.ScalarSubquery,
                         ast.QuantifiedComparison)):
        return True
    for f in getattr(node, "__dataclass_fields__", {}):
        v = getattr(node, f)
        if isinstance(v, ast.Query):
            continue
        if isinstance(v, ast.Node) and _contains_subquery(v):
            return True
        if isinstance(v, tuple):
            for item in v:
                if isinstance(item, ast.Node) and _contains_subquery(item):
                    return True
                if isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, ast.Node) and _contains_subquery(sub):
                            return True
    return False


def _factor_common_equi(e: Expr, outer_refs, sub_syms):
    """If `e` is a disjunction whose EVERY disjunct conjoins the same
    outer=inner equality, hoist those equalities out:
    `(k = o and A) or (k = o and B)` == `k = o and (A or B)`.
    Returns (pairs, rest Expr or None), or None when not factorable."""
    if not (isinstance(e, SpecialForm) and e.form == Form.OR):
        return None
    disjuncts = [split_ir_conjuncts(d) for d in e.args]
    first_keys = {c.key(): c for c in disjuncts[0]}
    common = []
    for k, c in first_keys.items():
        if all(any(x.key() == k for x in d) for d in disjuncts[1:]):
            pair = _as_equi_pair(c, outer_refs, sub_syms)
            if pair is not None:
                common.append((k, pair))
    if not common:
        return None
    common_keys = {k for k, _ in common}
    rests = []
    for d in disjuncts:
        kept = [c for c in d if c.key() not in common_keys]
        rests.append(ir.and_(*kept) if kept else Literal(True, T.BOOLEAN))
    if any(isinstance(x, Literal) and x.value is True for x in rests):
        rest = None  # some disjunct was ONLY the equalities: rest is TRUE
    else:
        rest = ir.or_(*rests)
    return [p for _, p in common], rest


def split_ir_conjuncts(e: Expr) -> list:
    if isinstance(e, SpecialForm) and e.form == Form.AND:
        out = []
        for a in e.args:
            out.extend(split_ir_conjuncts(a))
        return out
    return [e]


def _as_equi_pair(e: Expr, left_names, right_names):
    """If e is `lsym = rsym` with sides in the two given name sets, return the
    (left Symbol, right Symbol) pair (swapping as needed)."""
    if not (isinstance(e, Call) and e.name == "$eq"):
        return None
    a, b = e.args
    if not (isinstance(a, SymbolRef) and isinstance(b, SymbolRef)):
        return None
    if a.name in left_names and b.name in right_names:
        return (P.Symbol(a.name, a.type), P.Symbol(b.name, b.type))
    if b.name in left_names and a.name in right_names:
        return (P.Symbol(b.name, b.type), P.Symbol(a.name, a.type))
    return None


def _source_column(item) -> Optional[str]:
    """Column part of a plain `t.col` select item."""
    e = item.expr
    if isinstance(e, ast.Identifier) and len(e.parts) >= 2:
        return e.parts[-1]
    return None


def _source_alias(item) -> Optional[str]:
    """Qualifier of a plain `t.col` select item, kept on the output Field so
    ORDER BY `t.col` can re-match it after projection (also when the item is
    renamed: `SELECT t.col AS x ... ORDER BY t.col` is valid SQL)."""
    e = item.expr
    if isinstance(e, ast.Identifier) and len(e.parts) >= 2:
        return e.parts[-2]
    return None


def _name_hint(e: ast.Node) -> str:
    if isinstance(e, ast.Identifier):
        return e.parts[-1]
    if isinstance(e, ast.FunctionCall):
        return e.name
    return "expr"


def _frame_offset(bound: ast.FrameBound) -> Optional[int]:
    """Literal row offset relative to the current row (None = unbounded)."""
    if bound.kind in ("unbounded_preceding", "unbounded_following"):
        return None
    if bound.kind == "current":
        return 0
    if not isinstance(bound.value, ast.NumberLiteral):
        raise AnalysisError("window frame offset must be an integer literal")
    try:
        k = int(bound.value.text)
    except ValueError:
        raise AnalysisError("window frame offset must be an integer literal")
    if k < 0:
        raise AnalysisError("window frame offset must be non-negative")
    return -k if bound.kind == "preceding" else k


def _resolve_frame(wf, has_order: bool):
    """AST WindowFrame → (frame kind, start_off, end_off) for the executor.

    Reference: operator/window/FrameInfo.java + sql/analyzer checks in
    StatementAnalyzer.analyzeWindowFrame.  Unsupported frame shapes raise
    AnalysisError — a frame clause is never silently dropped.
    """
    if wf is None:
        return ("range" if has_order else "full"), None, 0
    s, e = wf.start.kind, wf.end.kind
    if s == "unbounded_following" or e == "unbounded_preceding":
        raise AnalysisError(f"invalid window frame {wf.kind} {s}..{e}")
    if s == "unbounded_preceding" and e == "unbounded_following":
        return "full", None, None
    if not has_order:
        if wf.kind in ("range", "groups") and s == "unbounded_preceding" and e == "current":
            # without ORDER BY all rows are peers: the running frame IS the
            # whole partition
            return "full", None, 0
        raise AnalysisError(
            "window frame requires ORDER BY in the window specification"
        )
    if wf.kind == "rows":
        start_off, end_off = _frame_offset(wf.start), _frame_offset(wf.end)
        if (
            start_off is not None
            and end_off is not None
            and start_off > end_off
        ):
            raise AnalysisError("window frame start is after frame end")
        return "rows", start_off, end_off
    # range/groups: only the frames equivalent to the running default are
    # computable on the peer-group machinery
    if s == "unbounded_preceding" and e == "current":
        return "range", None, 0
    raise AnalysisError(
        f"unsupported window frame {wf.kind} {s}..{e}"
    )
