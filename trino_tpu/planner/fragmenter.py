"""Exchange placement + plan fragmentation.

Reference: sql/planner/optimizations/AddExchanges.java:139 (distribution
choice), sql/planner/PlanFragmenter.java:116 (createSubPlans — cut the plan
at remote-exchange boundaries), SystemPartitioningHandle.java:41-57 (the
partitioning vocabulary), plan/RemoteSourceNode.java.

`add_exchanges` rewrites an optimized logical plan into a distributed form
with explicit ExchangeNodes; `create_subplans` cuts it into a SubPlan tree of
PlanFragments, each with a partitioning handle.  The distributed runner
executes fragments bottom-up: fragment bodies are SPMD programs over the
worker mesh, exchanges lower to ICI collectives (all_to_all / all_gather) or
a gather to the coordinator — never a silent fallback: every
coordinator-side fragment is explicit in the plan (EXPLAIN shows it).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from trino_tpu.planner import plan as P
from trino_tpu.planner.functions import HOLISTIC_AGGS, PARTITIONABLE_HOLISTIC
from trino_tpu.telemetry.decisions import record_decision

# -- partitioning handles (SystemPartitioningHandle.java:41-57) ---------------

SOURCE = "SOURCE"  # leaf: split-parallel scans
FIXED_HASH = "FIXED_HASH"  # rows hash-distributed on keys
FIXED_ARBITRARY = "FIXED_ARBITRARY"  # distributed, no key guarantee
SINGLE = "SINGLE"  # one task (the coordinator here)
COORDINATOR_ONLY = "COORDINATOR_ONLY"  # must run on the coordinator


@dataclass(frozen=True)
class PartitioningHandle:
    kind: str
    keys: tuple = ()  # Symbol names for FIXED_HASH

    def __str__(self):
        if self.keys:
            return f"{self.kind}[{', '.join(self.keys)}]"
        return self.kind


@dataclass
class RemoteSourceNode(P.PlanNode):
    """Consumer-side stand-in for a child fragment's output
    (reference: sql/planner/plan/RemoteSourceNode.java)."""

    fragment_id: int
    symbols: list  # output symbols (child fragment's root outputs)
    exchange_kind: str  # repartition | broadcast | gather | merge
    partition_symbols: list = field(default_factory=list)
    orderings: list = field(default_factory=list)  # merge exchanges
    #: plan-decision id carried from the cut ExchangeNode: the runtime
    #: applies this exchange under a matching decision_scope, so the
    #: collective's measured bytes join the placer's recorded choice
    decision_id: Optional[str] = None

    @property
    def outputs(self):
        return list(self.symbols)

    @property
    def children(self):
        return []

    def with_children(self, children):
        return self


@dataclass
class PlanFragment:
    """reference: sql/planner/plan/PlanFragment.java."""

    id: int
    root: P.PlanNode
    partitioning: PartitioningHandle


@dataclass
class SubPlan:
    """reference: sql/planner/SubPlan.java — fragment tree."""

    fragment: PlanFragment
    children: list

    def all_fragments(self):
        yield self.fragment
        for c in self.children:
            yield from c.all_fragments()


# -- AddExchanges -------------------------------------------------------------


class _Distribution:
    """Bottom-up distribution property of a subtree (PropertyDerivations
    analog): 'distributed' (rows spread over workers) or 'single'."""

    DISTRIBUTED = "distributed"
    SINGLE = "single"


class ExchangePlacer:
    """Insert ExchangeNodes so every operator's distribution requirement is
    met, choosing broadcast vs partitioned joins by stats (AddExchanges)."""

    def __init__(self, catalogs, properties=None, n_workers: int = 8,
                 colocate=None):
        from trino_tpu.partitioning import LayoutResolver
        from trino_tpu.runtime.session import SessionProperties

        self.catalogs = catalogs
        self.properties = properties or SessionProperties()
        self.n_workers = n_workers
        self.resolver = LayoutResolver(catalogs, self.properties)
        if colocate is not None:
            # executors whose data plane cannot honor hash placements
            # (the HTTP split_mod scheduler) force elision off regardless
            # of the session property
            self.colocate = bool(colocate)
        else:
            try:
                self.colocate = bool(self.properties.get("colocated_join"))
            except KeyError:  # pragma: no cover - older property sets
                self.colocate = True

    def _placements(self, node: P.PlanNode) -> tuple:
        from trino_tpu.partitioning import derive_partitioning

        if not self.colocate:
            return ()
        return derive_partitioning(node, self.resolver, self.n_workers)

    def place(self, node: P.PlanNode):
        self._register_scan_dictionaries(node)
        out, dist = self._visit(node)
        return out

    def _register_scan_dictionaries(self, node: P.PlanNode) -> None:
        """Eagerly register global dictionaries for every scanned string
        column (runtime/dictionary_service), not just join keys: the
        exchange serde then ships (key, version) refs instead of
        dictionary values for ANY distributed varchar column, and the
        prewarm manifest snapshots the assignment the workload actually
        ran under.  Connector dictionaries are cached, so this is one
        cheap fingerprint lookup per (table, column) per plan."""
        from trino_tpu.partitioning.properties import (
            derive_dictionary_coding,
        )

        for n in P.walk(node):
            if isinstance(n, P.TableScanNode):
                derive_dictionary_coding(n, self.resolver)

    # returns (node, distribution)
    def _visit(self, node: P.PlanNode):
        m = getattr(self, "_p_" + type(node).__name__, None)
        if m is not None:
            return m(node)
        # unknown node: run on the coordinator over gathered children
        return self._coordinator_only(node)

    def _coordinator_only(self, node: P.PlanNode):
        kids = []
        for c in node.children:
            child, dist = self._visit(c)
            kids.append(self._gathered(child, dist))
        return node.with_children(kids) if kids else node, _Distribution.SINGLE

    def _gathered(self, node: P.PlanNode, dist: str) -> P.PlanNode:
        if dist == _Distribution.SINGLE:
            return node
        return P.ExchangeNode(node, "gather")

    # -- leaves --

    def _p_TableScanNode(self, node):
        return node, _Distribution.DISTRIBUTED

    def _p_ValuesNode(self, node):
        return node, _Distribution.SINGLE

    # -- distribution-preserving unaries --

    def _inherit(self, node):
        child, dist = self._visit(node.children[0])
        return node.with_children([child]), dist

    _p_FilterNode = _inherit
    _p_ProjectNode = _inherit
    _p_SampleNode = _inherit  # Bernoulli sampling is row-local
    _p_UnnestNode = _inherit  # elementwise expansion: stays in its fragment

    def _p_OutputNode(self, node):
        child, dist = self._visit(node.source)
        return node.with_children([self._gathered(child, dist)]), _Distribution.SINGLE

    def _p_EnforceSingleRowNode(self, node):
        child, dist = self._visit(node.source)
        return node.with_children([self._gathered(child, dist)]), _Distribution.SINGLE

    # -- aggregation: partial below exchange, final above --

    def _p_AggregationNode(self, node: P.AggregationNode):
        child, dist = self._visit(node.source)
        if dist == _Distribution.SINGLE:
            return node.with_children([child]), _Distribution.SINGLE
        from trino_tpu.runtime.local_planner import supports_uniform_distinct

        has_distinct = any(a.distinct for _, a in node.aggregations)
        # uniform DISTINCT keeps its distributed shape: repartition on group
        # keys, per-worker dedupe + single-stage agg (the shared predicate
        # IS the _distinct_preagg support envelope)
        uniform_distinct = (
            has_distinct
            and bool(node.group_symbols)
            and supports_uniform_distinct(node)
        )
        needs_gather = (has_distinct and not uniform_distinct) or any(
            a.function in HOLISTIC_AGGS
            and a.function not in PARTITIONABLE_HOLISTIC
            for _, a in node.aggregations
        ) or (
            not node.group_symbols
            and any(
                a.function in HOLISTIC_AGGS for _, a in node.aggregations
            )
        )
        if needs_gather:
            # DISTINCT / collect aggregates (and global holistic aggs) need
            # the whole group on one node; the local engine handles them
            # after a gather
            return (
                node.with_children([self._gathered(child, dist)]),
                _Distribution.SINGLE,
            )
        # NOTE: grouped percentile does NOT gather — a hash repartition on
        # the group keys co-locates each whole group, so the executor runs
        # the single-stage sort-based percentile per worker (the reference's
        # single-step aggregation over hash distribution)
        if node.group_symbols:
            # exchange elision: when the child is already placed on a
            # subset of the grouping keys (a bucketed scan or an upstream
            # repartition), every group is whole on one worker — run the
            # aggregation single-stage with NO exchange (the reference's
            # partitioning-matching in AddExchanges)
            if not has_distinct and not any(
                a.function in HOLISTIC_AGGS for _, a in node.aggregations
            ):
                gnames = {s.name for s in node.group_symbols}
                if any(
                    t and set(t) <= gnames for t in self._placements(child)
                ):
                    record_decision(
                        "exchange", "planner.agg_placement", "elide",
                        "repartition", {"group_keys": sorted(gnames)},
                    )
                    return (
                        node.with_children([child]),
                        _Distribution.DISTRIBUTED,
                    )
            # the executor pushes the PARTIAL step to the producing side of
            # the exchange and runs FINAL above it (the
            # PushPartialAggregationThroughExchange effect)
            did = record_decision(
                "exchange", "planner.agg_placement", "repartition", "gather",
                {"group_keys": [s.name for s in node.group_symbols]},
            )
            ex = P.ExchangeNode(
                child, "repartition", list(node.group_symbols),
                decision_id=did,
            )
            return node.with_children([ex]), _Distribution.DISTRIBUTED
        # global aggregation: partial states per worker, gathered + merged
        ex = P.ExchangeNode(child, "gather")
        return node.with_children([ex]), _Distribution.SINGLE

    # -- joins --

    def _p_JoinNode(self, node: P.JoinNode):
        from trino_tpu.planner.stats import estimate_rows

        if node.kind == "right":
            # distribute as the flipped LEFT join (the local engine performs
            # the same flip; symbol resolution is by name, so output order
            # does not matter at this level)
            node = P.JoinNode(
                "left",
                node.right,
                node.left,
                [(r, l) for l, r in node.criteria],
                node.filter,
                node.distribution,
            )
            # the ORIGINAL certificate described the pre-flip build side,
            # so it cannot be carried verbatim — but the flipped node is a
            # plain left join whose own proof (the old LEFT side's
            # uniqueness/multiplicity) is derivable right here.  Without
            # this, every mirrored plan shape the optimizer emits loses
            # its license and pays the runtime sizing path.
            from trino_tpu.verify.capacity import derive_join_certificate

            node.capacity_cert = derive_join_certificate(node, self.catalogs)
        left, ldist = self._visit(node.left)
        right, rdist = self._visit(node.right)
        supported = node.kind in ("inner", "left", "full") and node.criteria
        if not supported or ldist == _Distribution.SINGLE:
            return (
                node.with_children(
                    [self._gathered(left, ldist), self._gathered(right, rdist)]
                ),
                _Distribution.SINGLE,
            )
        pref = self.properties.get("join_distribution_type").upper()
        limit = self.properties.get("broadcast_join_rows")
        est = estimate_rows(node.right, self.catalogs)
        broadcast = pref == "BROADCAST" or (
            pref == "AUTOMATIC" and est is not None and est <= limit
        )
        if node.kind == "full":
            # a broadcast FULL join would emit the unmatched build tail once
            # PER WORKER; repartitioning keeps every build row on exactly
            # one worker (reference: AddExchanges forces partitioned for
            # full/right joins)
            broadcast = False
        # decision-ledger inputs: exactly what this rule saw when it chose
        # (telemetry/decisions) — the hindsight join compares the measured
        # collective bytes against the rejected alternative's estimate
        inputs = {
            "join_kind": node.kind,
            "estimated_build_rows": est,
            "broadcast_join_rows": limit,
            "join_distribution_type": pref,
        }
        if broadcast and self.colocate:
            # partitioning matching beats the stats heuristic: when the
            # PROBE side is already placed on its keys (bucketed layout or
            # upstream exchange), a partitioned join moves at most the
            # build side once — strictly less than W broadcast copies; a
            # fully co-located join moves nothing at all
            lex, rex, dist = self._partitioned_join_sides(
                left, right, node.criteria
            )
            if dist == "colocated" or lex is left:
                did = record_decision(
                    "join_distribution", "planner.add_exchanges", dist,
                    "broadcast", inputs,
                )
                self._stamp(lex, did)
                self._stamp(rex, did)
                return (
                    P.JoinNode(
                        node.kind, lex, rex, node.criteria, node.filter,
                        dist, node.capacity_cert, did,
                    ),
                    _Distribution.DISTRIBUTED,
                )
        if broadcast:
            did = record_decision(
                "join_distribution", "planner.add_exchanges", "broadcast",
                "partitioned", inputs,
            )
            ex = P.ExchangeNode(right, "broadcast", decision_id=did)
            out = P.JoinNode(
                node.kind, left, ex, node.criteria, node.filter,
                "broadcast", node.capacity_cert, did,
            )
        else:
            lex, rex, dist = self._partitioned_join_sides(
                left, right, node.criteria
            )
            did = record_decision(
                "join_distribution", "planner.add_exchanges", dist,
                "broadcast", inputs,
            )
            self._stamp(lex, did)
            self._stamp(rex, did)
            out = P.JoinNode(
                node.kind, lex, rex, node.criteria, node.filter, dist,
                node.capacity_cert, did,
            )
        return out, _Distribution.DISTRIBUTED

    @staticmethod
    def _stamp(node, decision_id) -> None:
        """Attribute an exchange the placer just inserted to a decision
        (never overwrites: an exchange belongs to exactly one choice)."""
        if (
            isinstance(node, P.ExchangeNode)
            and node.decision_id is None
        ):
            node.decision_id = decision_id

    def _partitioned_join_sides(self, left, right, criteria):
        """Exchange placement for a partitioned join, with partitioning
        matching: a side already placed on (a subset of) its join keys
        keeps its placement and skips the repartition; when BOTH sides
        share an aligned placement the join is fully co-located.  The
        repartitioned side hashes the keys ALIGNED with the placed side's
        tuple, so equal-key rows of the two sides land on one worker.

        String keys participate ONLY when both sides carry the same
        versioned global dictionary assignment (`derive_dictionary_coding`)
        — the version gate that makes varchar keys co-locate like integer
        keys without ever trusting producer-local codes."""
        from trino_tpu.partitioning import (
            align_through_criteria,
            derive_dictionary_coding,
            hash_aligned_criteria,
        )

        lprops = self._placements(left)
        rprops = self._placements(right)
        coding = dict(derive_dictionary_coding(left, self.resolver))
        coding.update(derive_dictionary_coding(right, self.resolver))
        aligned = hash_aligned_criteria(criteria, coding)
        # dictionary-coding placement lift: versioned varchar keys that
        # participate in hash alignment like integers — a choice worth a
        # ledger entry, because the rejected alternative (dropping the
        # string keys from the alignment) forces a wider repartition
        from trino_tpu import types as T

        coded = [
            f"{l.name}={r.name}"
            for l, r in aligned
            if T.is_string_kind(l.type)
        ]
        if coded:
            record_decision(
                "dictionary_placement", "planner.partitioned_join_sides",
                "coded_colocate", "exclude_varchar_keys",
                {"keys": coded},
            )
        l2r = {l.name: r for l, r in aligned}
        for tl in lprops:
            if tl and all(n in l2r for n in tl):
                tr = tuple(l2r[n].name for n in tl)
                if tr in rprops:
                    return left, right, "colocated"
        lal = align_through_criteria(lprops, criteria, True, coding)
        if lal is not None:
            _, other = lal
            return left, P.ExchangeNode(right, "repartition", list(other)), "partitioned"
        ral = align_through_criteria(rprops, criteria, False, coding)
        if ral is not None:
            _, other = ral
            return P.ExchangeNode(left, "repartition", list(other)), right, "partitioned"
        return (
            P.ExchangeNode(left, "repartition", [l for l, _ in criteria]),
            P.ExchangeNode(right, "repartition", [r for _, r in criteria]),
            "partitioned",
        )

    def _p_SemiJoinNode(self, node: P.SemiJoinNode):
        src, sdist = self._visit(node.source)
        filt, fdist = self._visit(node.filtering)
        if sdist == _Distribution.SINGLE:
            return (
                node.with_children(
                    [self._gathered(src, sdist), self._gathered(filt, fdist)]
                ),
                _Distribution.SINGLE,
            )
        if node.filter is not None:
            # residual-filtered semi join: repartition BOTH sides on the key
            # so every key-matching candidate pair is co-located; the
            # residual evaluates per shard (reference: AddExchanges semi join
            # partitioned distribution)
            did = record_decision(
                "join_distribution", "planner.semijoin", "partitioned",
                "broadcast",
                {"residual": True, "key": node.source_key.name},
            )
            sex = P.ExchangeNode(
                src, "repartition", [node.source_key], decision_id=did
            )
            fex = P.ExchangeNode(
                filt, "repartition", [node.filtering_key], decision_id=did
            )
            out = node.with_children([sex, fex])
            out.decision_id = did
            return out, _Distribution.DISTRIBUTED
        did = record_decision(
            "join_distribution", "planner.semijoin", "broadcast",
            "partitioned",
            {"residual": False, "key": node.source_key.name},
        )
        ex = P.ExchangeNode(filt, "broadcast", decision_id=did)
        out = node.with_children([src, ex])
        out.decision_id = did
        return out, _Distribution.DISTRIBUTED

    # -- sorting / limiting: partial per worker + merge/gather + final --

    def _p_SortNode(self, node: P.SortNode):
        child, dist = self._visit(node.source)
        if dist == _Distribution.SINGLE:
            return node.with_children([child]), _Distribution.SINGLE
        partial = P.SortNode(child, node.orderings)
        ex = P.ExchangeNode(partial, "merge", [], list(node.orderings))
        return ex, _Distribution.SINGLE

    def _p_TopNNode(self, node: P.TopNNode):
        child, dist = self._visit(node.source)
        if dist == _Distribution.SINGLE:
            return node.with_children([child]), _Distribution.SINGLE
        partial = P.TopNNode(child, node.orderings, node.count)
        ex = P.ExchangeNode(partial, "merge", [], list(node.orderings))
        return P.TopNNode(ex, node.orderings, node.count), _Distribution.SINGLE

    def _p_LimitNode(self, node: P.LimitNode):
        child, dist = self._visit(node.source)
        if dist == _Distribution.SINGLE:
            return node.with_children([child]), _Distribution.SINGLE
        if node.count is None:  # OFFSET-only: no partial-limit benefit
            return (
                P.LimitNode(self._gathered(child, dist), None, node.offset),
                _Distribution.SINGLE,
            )
        # per-worker partial limit keeps offset+count rows; final applies both
        partial = P.LimitNode(child, node.count + node.offset)
        ex = P.ExchangeNode(partial, "gather")
        return P.LimitNode(ex, node.count, node.offset), _Distribution.SINGLE

    # -- window: repartition on partition keys --

    def _p_WindowNode(self, node: P.WindowNode):
        child, dist = self._visit(node.source)
        if dist == _Distribution.SINGLE:
            return node.with_children([child]), _Distribution.SINGLE
        if not node.partition_by:
            # whole-input window: single partition must see every row
            return (
                node.with_children([self._gathered(child, dist)]),
                _Distribution.SINGLE,
            )
        did = record_decision(
            "exchange", "planner.window", "repartition", "gather",
            {"partition_by": [s.name for s in node.partition_by]},
        )
        ex = P.ExchangeNode(
            child, "repartition", list(node.partition_by), decision_id=did
        )
        return node.with_children([ex]), _Distribution.DISTRIBUTED

    def _p_MarkDistinctNode(self, node):
        child, dist = self._visit(node.source)
        if dist == _Distribution.SINGLE:
            return node.with_children([child]), _Distribution.SINGLE
        # repartition on the full key set: every distinct combination lands
        # wholly on one worker, so first-occurrence marks are globally unique
        did = record_decision(
            "exchange", "planner.mark_distinct", "repartition", "gather",
            {"keys": [s.name for s in node.key_symbols]},
        )
        ex = P.ExchangeNode(
            child, "repartition", list(node.key_symbols), decision_id=did
        )
        return node.with_children([ex]), _Distribution.DISTRIBUTED

    # -- set operations --

    def _p_UnionNode(self, node: P.UnionNode):
        kids = []
        dists = []
        for c in node.children:
            k, d = self._visit(c)
            kids.append(k)
            dists.append(d)
        if all(d == _Distribution.SINGLE for d in dists):
            return node.with_children(kids), _Distribution.SINGLE
        # mixed: gather everything (UNION semantics are arbitrary-ordered, a
        # distributed union would also be fine; coordinator concat is exact)
        kids = [self._gathered(k, d) for k, d in zip(kids, dists)]
        return node.with_children(kids), _Distribution.SINGLE

    def _p_ExchangeNode(self, node: P.ExchangeNode):
        return self._inherit(node)


def _verify_mode(properties) -> str:
    from trino_tpu import verify as V

    mode = None
    if properties is not None:
        try:
            mode = properties.get("verify_plan")
        except KeyError:  # pragma: no cover - older property sets
            mode = None
    return V.resolve_mode(mode)


def add_exchanges(plan: P.OutputNode, catalogs, properties=None,
                  n_workers: int = 8, colocate=None):
    from trino_tpu import verify as V

    placer = ExchangePlacer(catalogs, properties, n_workers, colocate=colocate)
    out = placer.place(plan)
    assert isinstance(out, P.OutputNode)
    # distributed invariants: every ExchangeNode's partition symbols exist
    # with hashable dtypes, no placement broke dependencies, and every
    # elided exchange is backed by a producing layout or exchange
    mode = _verify_mode(properties)
    if mode != "off":
        from trino_tpu.verify.partitioning import check_partitioning

        V.enforce(V.check_plan(out), mode)
        V.enforce(
            check_partitioning(out, placer.resolver, n_workers), mode
        )
    return out


# -- PlanFragmenter -----------------------------------------------------------


class _Fragmenter:
    def __init__(self, resolver=None, n_workers: int = 8):
        self.next_id = 0
        self.resolver = resolver
        self.n_workers = n_workers

    def fragment(self, root: P.PlanNode) -> SubPlan:
        """Cut at every ExchangeNode; the subtree below each exchange becomes
        a child fragment, replaced by a RemoteSourceNode in the parent."""
        children: list[SubPlan] = []

        def cut(node: P.PlanNode) -> P.PlanNode:
            if isinstance(node, P.ExchangeNode):
                child_sub = self.fragment(node.source)
                children.append(child_sub)
                return RemoteSourceNode(
                    child_sub.fragment.id,
                    list(node.source.outputs),
                    node.kind,
                    list(node.partition_symbols),
                    list(node.orderings),
                    node.decision_id,
                )
            kids = node.children
            if not kids:
                return node
            return node.with_children([cut(c) for c in kids])

        body = cut(root)
        fid = self.next_id
        self.next_id += 1
        part = _fragment_partitioning(body, self.resolver, self.n_workers)
        sub = SubPlan(PlanFragment(fid, body, part), children)
        return sub


def _fragment_partitioning(
    body: P.PlanNode, resolver=None, n_workers: int = 8
) -> PartitioningHandle:
    """Derive the fragment's partitioning handle from its body.  SOURCE
    fragments report their layout-derived partition symbols (when the
    resolver finds a usable bucketed layout), so EXPLAIN (TYPE DISTRIBUTED)
    makes layout decisions auditable without reading planner internals."""
    has_scan = any(isinstance(n, P.TableScanNode) for n in P.walk(body))
    remotes = [n for n in P.walk(body) if isinstance(n, RemoteSourceNode)]
    if has_scan:
        keys: tuple = ()
        if resolver is not None:
            from trino_tpu.partitioning import derive_partitioning

            props = derive_partitioning(body, resolver, n_workers)
            if props:
                keys = props[0]
        return PartitioningHandle(SOURCE, keys)
    for r in remotes:
        if r.exchange_kind == "repartition":
            return PartitioningHandle(
                FIXED_HASH, tuple(s.name for s in r.partition_symbols)
            )
    for r in remotes:
        if r.exchange_kind in ("gather", "merge"):
            return PartitioningHandle(SINGLE)
        if r.exchange_kind == "broadcast":
            return PartitioningHandle(FIXED_ARBITRARY)
    return PartitioningHandle(COORDINATOR_ONLY)


def create_subplans(
    distributed_plan: P.PlanNode,
    properties=None,
    catalogs=None,
    n_workers: int = 8,
) -> SubPlan:
    from trino_tpu import verify as V
    from trino_tpu.partitioning import LayoutResolver

    resolver = LayoutResolver(catalogs, properties)
    sub = _Fragmenter(resolver, n_workers).fragment(distributed_plan)
    # fragment invariants: unique fragment ids, every RemoteSourceNode names
    # an existing fragment whose root outputs match symbol-for-symbol —
    # plus the collective-uniformity pass: every distributed fragment's
    # statically enumerated collective sequence is divergence-free (never
    # conditional on per-worker data), so an SPMD program can't hang the
    # mesh on a collective one worker skips
    mode = _verify_mode(properties)
    if mode != "off":
        from trino_tpu.verify.collectives import check_collective_uniformity

        V.enforce(V.check_subplan(sub), mode)
        V.enforce(check_collective_uniformity(sub), mode)
    return sub


def fragment_text(sub: SubPlan) -> str:
    """EXPLAIN (TYPE DISTRIBUTED) rendering (planprinter role)."""
    lines = []

    def render(s: SubPlan):
        lines.append(f"Fragment {s.fragment.id} [{s.fragment.partitioning}]")
        body = P.plan_text(s.fragment.root, indent=1)
        lines.append(body.rstrip("\n"))
        for c in s.children:
            render(c)

    render(sub)
    return "\n".join(lines) + "\n"
