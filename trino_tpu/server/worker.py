"""Worker server: remote task execution over HTTP (the multi-host tier).

Reference roles: server/SqlTaskManager + TaskResource (/v1/task REST API) on
the worker side, TaskExecutor for the execution slot, and the HTTP data
plane of exchange/ExchangeClient: task outputs are partitioned buckets that
downstream tasks PULL with GET /v1/task/{id}/results/{bucket}.

The multi-host layer complements the in-mesh SPMD path: intra-host
parallelism is XLA collectives over the device mesh (parallel/runner.py);
inter-host distribution is fragments shipped to worker processes with HTTP
exchanges — the DCN tier, matching the reference's worker-to-worker shuffle.

Wire format: pickled plan fragments (intra-cluster traffic, the role of the
reference's internal thrift/json codecs) + PagesSerde buckets
(parallel/serde.py).  Because unpickling executes code, task submissions are
authenticated: when TRINO_TPU_CLUSTER_SECRET is set, every POST /v1/task must
carry an HMAC-SHA256 of the body under X-Cluster-Auth (the internal-
communication shared-secret analog of the reference's
internal-communication.shared-secret).  Binding to a non-loopback interface
REQUIRES the secret; the default loopback bind works without one.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import pickle
import threading
import time
import traceback
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence


def cluster_secret() -> Optional[bytes]:
    """Shared intra-cluster secret (reference:
    internal-communication.shared-secret)."""
    s = os.environ.get("TRINO_TPU_CLUSTER_SECRET")
    return s.encode() if s else None


def sign_body(secret: bytes, body: bytes) -> str:
    return _hmac.new(secret, body, hashlib.sha256).hexdigest()


class WorkerDraining(RuntimeError):
    """A submission raced past the handler's DRAINING fast-path but lost
    the atomic admission check in `WorkerServer.submit` — mapped to the
    same 503 the fast path answers."""


@dataclass
class TaskDescriptor:
    """One fragment execution on one worker."""

    task_id: str
    fragment_root: object  # PlanNode
    output_symbols: list
    #: RemoteSourceNode inputs: fragment_id -> list of result URLs (one per
    #: producing task; the bucket for THIS task is already in the URL)
    inputs: dict = field(default_factory=dict)
    #: output partitioning: (channels, n_buckets) or None for a single bucket
    output_partitioning: Optional[tuple] = None
    #: split assignment for leaf scans: (worker_index, total_workers)
    split_mod: Optional[tuple] = None
    #: session properties to apply
    properties: dict = field(default_factory=dict)
    #: cross-fragment dynamic filters: probe symbol name -> (lo, hi) raw
    #: device-representation bounds (reference: DynamicFilterService summary
    #: delivery into task descriptors)
    dynamic_ranges: dict = field(default_factory=dict)
    #: compute the dynamic-filter range summary for this task's output
    #: (set only on build-side fragments the coordinator will query)
    collect_ranges: bool = False
    #: seconds the owning query had left at submission (None = unbounded);
    #: bounds the task's own run AND its input-pull HTTP timeouts, so a
    #: worker never outlives the query that scheduled it (reference:
    #: HttpRemoteTask's per-request deadline derivation)
    deadline_s: Optional[float] = None
    #: coordinator trace context: (query_id, parent span id) — rides the
    #: descriptor the same way deadline_s does (the W3C traceparent analog
    #: of the reference's opentelemetry context propagation).  The worker
    #: opens its task/execution spans under it and serves the finished tree
    #: at GET /v1/task/{id}/spans for the coordinator to merge.
    trace_context: Optional[tuple] = None


class _FilteringConnector:
    """Delegates to a connector but serves only splits with
    seq % total == index (the coordinator's split assignment)."""

    def __init__(self, inner, index: int, total: int):
        self._inner = inner
        self._index = index
        self._total = total

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def splits(self, handle, target_splits, predicate=None):
        out = [
            s
            for s in self._inner.splits(
                handle, target_splits=max(target_splits, self._total),
                predicate=predicate,
            )
            if s.seq % self._total == self._index
        ]
        return out


class _Task:
    def __init__(self, desc: TaskDescriptor):
        from trino_tpu.runtime.lifecycle import QueryContext

        self.desc = desc
        self.state = "RUNNING"
        self.error: Optional[str] = None
        self.buckets: list = []
        #: nested span tree of this task's execution (Span.to_dict form),
        #: set at completion when the descriptor carried a trace context;
        #: the coordinator grafts it under its fragment span
        self.spans: Optional[dict] = None
        #: per-output-symbol (lo, hi) value bounds of this task's result
        #: (the dynamic-filter summary the coordinator may collect)
        self.ranges: dict = {}
        self.done = threading.Event()
        #: task-local lifecycle handle: DELETE /v1/task/{id} cancels it, the
        #: descriptor deadline bounds it, and cooperative checks inside the
        #: execution abort through it
        self.lifecycle = QueryContext(
            desc.task_id, max_run_time_s=desc.deadline_s or 0.0
        )


class WorkerServer:
    """One worker process: accepts tasks, executes fragments, serves
    result buckets."""

    def __init__(
        self,
        catalogs=None,
        port: int = 0,
        host: str = "127.0.0.1",
        max_concurrent_tasks: Optional[int] = None,
        coordinator_url: Optional[str] = None,
    ):
        from trino_tpu.config import get_config
        from trino_tpu.connectors.api import default_catalogs

        if max_concurrent_tasks is None:
            max_concurrent_tasks = get_config().worker.max_concurrent_tasks
        self.catalogs = catalogs or default_catalogs()
        self._tasks: dict[str, _Task] = {}
        #: TaskExecutor analog (reference: execution/executor/
        #: TaskExecutor.java): a bounded number of concurrently RUNNING
        #: tasks; excess submissions queue on the semaphore instead of
        #: oversubscribing the host
        self._slots = threading.Semaphore(max(1, max_concurrent_tasks))
        #: graceful-shutdown state (GracefulShutdownHandler role): ACTIVE
        #: serves everything; DRAINING finishes running tasks, refuses new
        #: submissions with 503 (REFUSED semantics on the client), then
        #: exits once idle.  `drained` is set when the last task finished.
        self.state = "ACTIVE"
        self._state_lock = threading.Lock()
        self.drained = threading.Event()
        #: injectable for tests (the drain-grace linger must not slow them)
        self._sleep = time.sleep
        #: injectable clock: the drain waiter's wait+grace bound and its
        #: force-kill escalation run deterministically in tier-1
        self._clock = time.monotonic
        #: coordinator to announce to at start (auto-rejoin); falls back to
        #: the `worker.coordinator-url` config knob
        self._coordinator_url = coordinator_url
        # global dictionary refs shipped in exchange pages resolve against
        # this worker's own catalogs first (generated catalogs re-derive
        # deterministically); anything else is pulled from the coordinator
        from trino_tpu.runtime.dictionary_service import (
            DICTIONARY_SERVICE,
            coordinator_fetch_hook,
        )

        DICTIONARY_SERVICE.attach_catalogs(self.catalogs)
        coord = coordinator_url or get_config().worker.coordinator_url
        if coord:
            DICTIONARY_SERVICE.fetch_hook = coordinator_fetch_hook(coord)
        #: set once a register announce succeeded (test/ops evidence)
        self.registered = threading.Event()
        self._secret = cluster_secret()
        if host not in ("127.0.0.1", "localhost") and self._secret is None:
            raise ValueError(
                "non-loopback worker bind requires TRINO_TPU_CLUSTER_SECRET "
                "(task submissions are code-executing pickles)"
            )
        self._host = host
        worker = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _bytes(self, code: int, body: bytes, ctype="application/octet-stream"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != "/v1/task":
                    return self._bytes(404, b"not found", "text/plain")
                if worker.lifecycle_state() != "ACTIVE":
                    # draining: refuse BEFORE reading/unpickling — the
                    # coordinator's submit maps 503 to REFUSED (skip this
                    # worker, never retry it) and re-plans without us
                    return self._bytes(503, b"DRAINING", "text/plain")
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                secret = worker._secret
                if secret is not None:
                    sig = self.headers.get("X-Cluster-Auth", "")
                    if not _hmac.compare_digest(sig, sign_body(secret, body)):
                        # reject BEFORE unpickling: the codec executes code
                        return self._bytes(401, b"bad signature", "text/plain")
                desc = pickle.loads(body)
                try:
                    t = worker.submit(desc)
                except WorkerDraining:
                    # lost the race with begin_drain's state flip: same
                    # refusal as the fast path above
                    return self._bytes(503, b"DRAINING", "text/plain")
                self._bytes(200, t.desc.task_id.encode(), "text/plain")

            def do_PUT(self):
                if self.path != "/v1/worker/shutdown":
                    return self._bytes(404, b"not found", "text/plain")
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                secret = worker._secret
                if secret is not None:
                    # shutdown is as privileged as task submission: same
                    # HMAC gate (an unauthenticated PUT per worker would
                    # let any peer drain the whole cluster)
                    sig = self.headers.get("X-Cluster-Auth", "")
                    if not _hmac.compare_digest(sig, sign_body(secret, body)):
                        return self._bytes(401, b"bad signature", "text/plain")
                # graceful drain (GracefulShutdownHandler analog): answer
                # immediately; a background waiter finishes running tasks,
                # sets `drained`, and shuts the server down
                worker.begin_drain()
                self._bytes(200, b"DRAINING", "text/plain")

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if parts == ["v1", "info"]:
                    body = ('{"state": "%s"}' % worker.lifecycle_state()).encode()
                    self._bytes(200, body, "application/json")
                    return
                if parts == ["v1", "metrics"]:
                    # same Prometheus surface as the coordinator, so one
                    # scrape config covers both tiers
                    from trino_tpu.telemetry import REGISTRY

                    self._bytes(
                        200,
                        REGISTRY.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    return
                if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                    t = worker.task(parts[2])
                    if t is None:
                        return self._bytes(404, b"no such task", "text/plain")
                    t.done.wait(timeout=status_wait_default())
                    body = (
                        t.state
                        if t.error is None
                        else f"{t.state}\n{t.error}"
                    ).encode()
                    return self._bytes(200, body, "text/plain")
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "task"]
                    and parts[3] == "spans"
                ):
                    # cross-host tracing pull: the finished task's span tree
                    # (Span.to_dict form, worker-local clock) for the
                    # coordinator to graft under its fragment span; null
                    # when the descriptor carried no trace context
                    t = worker.task(parts[2])
                    if t is None:
                        return self._bytes(404, b"no such task", "text/plain")
                    t.done.wait(timeout=_result_wait_s(t))
                    import json as _json

                    return self._bytes(
                        200, _json.dumps(t.spans).encode(), "application/json"
                    )
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "task"]
                    and parts[3] == "dynamic"
                ):
                    t = worker.task(parts[2])
                    if t is None:
                        return self._bytes(404, b"no such task", "text/plain")
                    t.done.wait(timeout=_result_wait_s(t))
                    import json as _json

                    return self._bytes(
                        200, _json.dumps(t.ranges).encode(), "application/json"
                    )
                if (
                    len(parts) == 5
                    and parts[:2] == ["v1", "task"]
                    and parts[3] == "results"
                ):
                    t = worker.task(parts[2])
                    if t is None:
                        return self._bytes(404, b"no such task", "text/plain")
                    t.done.wait(timeout=_result_wait_s(t))
                    if t.state != "FINISHED":
                        return self._bytes(
                            500, (t.error or "task failed").encode(), "text/plain"
                        )
                    bucket = int(parts[4])
                    return self._bytes(200, t.buckets[bucket])
                self._bytes(404, b"not found", "text/plain")

            def do_DELETE(self):
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                    t = worker.pop_task(parts[2])
                    if t is not None:
                        # REAL cancel: a running task aborts at its next
                        # cooperative check instead of burning the slot
                        t.lifecycle.cancel("task canceled by coordinator")
                self._bytes(200, b"ok", "text/plain")

        self._httpd = ThreadingHTTPServer((self._host, port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self) -> "WorkerServer":
        from trino_tpu.config import get_config

        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="worker"
        )
        self._thread.start()
        # auto-rejoin (reference: DiscoveryNodeManager announcement): a
        # RESTARTED worker resurrects its membership entry by announcing
        # itself — no operator action.  Background + best-effort: a worker
        # must come up even while its coordinator is still restarting.
        coord = self._coordinator_url or get_config().worker.coordinator_url
        if coord:
            threading.Thread(
                target=self.announce, args=(coord,), daemon=True,
                name="worker-register",
            ).start()
        return self

    def announce(self, coordinator_url: str,
                 attempts: Optional[int] = None) -> bool:
        """PUT /v1/worker/register at the coordinator (HMAC'd when the
        cluster secret is set), with backed-off retries so a worker that
        restarts FASTER than its coordinator still rejoins."""
        from trino_tpu.config import get_config
        from trino_tpu.runtime.retry import Backoff

        cfg = get_config()
        body = self.url.encode()
        headers = {}
        if self._secret is not None:
            headers["X-Cluster-Auth"] = sign_body(self._secret, body)
        backoff = Backoff(
            base_s=cfg.remote.backoff_base_s, cap_s=cfg.remote.backoff_cap_s,
            sleep=self._sleep,
        )
        n = attempts if attempts is not None else cfg.remote.submit_attempts
        for attempt in range(max(1, n)):
            if attempt:
                backoff.wait(attempt - 1)
            req = urllib.request.Request(
                f"{coordinator_url}/v1/worker/register", data=body,
                headers=headers, method="PUT",
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=cfg.lifecycle.probe_timeout_s
                ) as r:
                    r.read()
            except Exception:
                continue
            self.registered.set()
            return True
        return False

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def begin_drain(self, exit_on_idle: bool = True) -> None:
        """Graceful shutdown (reference: GracefulShutdownHandler, SURVEY
        §5.3): flip to DRAINING (new submissions get 503/REFUSED), wait for
        running tasks to finish under ONE shared `worker.drain-task-wait`
        deadline, set `drained`, linger for `worker.drain-grace` seconds so
        downstream consumers can still PULL the finished tasks' results
        (task completion is not result delivery — the reference sleeps out
        a grace period for exactly this reason), then stop the HTTP server.

        Forced-kill escalation: tasks still running when the wait expires
        are canceled through their task-lifecycle tokens (they abort at
        their next cooperative check, with the grace window to honor it)
        and the server exits REGARDLESS — total drain time is bounded by
        wait + grace, so a wedged task can never wedge a drain.
        Idempotent — a second PUT while draining is a no-op."""
        with self._state_lock:
            if self.state != "ACTIVE":
                return
            self.state = "DRAINING"
            # snapshot under the same lock submit() admits under: every
            # task that slipped in before the flip is in it
            running = list(self._tasks.values())
        worker = self

        def waiter():
            from trino_tpu.config import get_config
            from trino_tpu.telemetry.metrics import drain_force_kills_counter

            cfg = get_config().worker
            deadline = worker._clock() + cfg.drain_task_wait_s
            for t in running:
                t.done.wait(timeout=max(0.0, deadline - worker._clock()))
            for t in running:
                if not t.done.is_set():
                    # the escalation: a cooperative task aborts inside the
                    # grace window; a truly wedged one is abandoned when
                    # the server exits below — either way the drain ends
                    t.lifecycle.cancel(
                        "drain force-kill: worker.drain-task-wait expired"
                    )
                    drain_force_kills_counter().inc()
            worker.drained.set()
            if exit_on_idle:
                self._sleep(cfg.drain_grace_s)
                try:
                    worker.shutdown()
                except Exception:
                    pass

        threading.Thread(target=waiter, daemon=True, name="drain").start()

    # -- task registry (locked accessors: the HTTP handler threads and the
    # drain waiter share _tasks with submit; every touch goes through
    # _state_lock so the drain snapshot can never race a handler mutation) --

    def task(self, task_id: str) -> Optional[_Task]:
        with self._state_lock:
            return self._tasks.get(task_id)

    def pop_task(self, task_id: str) -> Optional[_Task]:
        with self._state_lock:
            return self._tasks.pop(task_id, None)

    def lifecycle_state(self) -> str:
        """ACTIVE | DRAINING for /v1/info (the detector's probe surface)."""
        with self._state_lock:
            return self.state

    # -- task execution (SqlTaskExecution role) ------------------------------

    def submit(self, desc: TaskDescriptor) -> _Task:
        t = _Task(desc)
        # admission is atomic with the drain flip: a submission that read
        # ACTIVE before begin_drain either registers HERE (so the drain
        # waiter's snapshot sees it and waits for it) or observes DRAINING
        # and is refused — no task can slip past the waiter's snapshot
        with self._state_lock:
            if self.state != "ACTIVE":
                raise WorkerDraining(f"worker is {self.state}")
            self._tasks[desc.task_id] = t
        threading.Thread(
            target=self._run, args=(t,), daemon=True, name=desc.task_id
        ).start()
        return t

    def _run(self, t: _Task) -> None:
        from trino_tpu.runtime.lifecycle import (
            QueryAbortedException,
            reset_current,
            set_current,
        )
        from trino_tpu.telemetry import NULL_TRACER, SpanTracer

        self._slots.acquire()
        # publish the task's lifecycle handle in THIS worker thread: the
        # execution's cooperative checks and its input-pull HTTP timeouts
        # (request_timeout) derive from the task deadline
        token = set_current(t.lifecycle)
        # cross-host tracing: the descriptor's trace context makes this
        # task's spans part of the coordinator's query trace (PR-4 carried
        # gap: multi-host tasks emitted no spans at all)
        tc = t.desc.trace_context
        tracer = SpanTracer(query_id=tc[0]) if tc else NULL_TRACER
        try:
            with tracer.span(
                "task", task_id=t.desc.task_id, worker=self.url,
                coordinator_span=(tc[1] if tc else None),
            ):
                t.buckets, t.ranges = self._execute(t.desc, tracer=tracer)
            t.state = "FINISHED"
        except QueryAbortedException as e:
            t.state = "CANCELED"
            t.error = str(e)
        except Exception:
            t.state = "FAILED"
            t.error = traceback.format_exc()
        finally:
            if tracer.enabled and tracer.root is not None:
                t.spans = tracer.root.to_dict()
            # a task aborted mid-wave must release its spill partitions
            # now, not when the abandoned wave generator is GC'd
            t.lifecycle.release_spills()
            reset_current(token)
            self._slots.release()
            t.done.set()

    def _execute(self, desc: TaskDescriptor, tracer=None) -> list:
        from trino_tpu.columnar.batch import concat_batches
        from trino_tpu.parallel.serde import (
            batches_to_bytes,
            bytes_to_batches,
            partition_batches,
        )
        from trino_tpu.planner.fragmenter import RemoteSourceNode
        from trino_tpu.runtime.local_planner import (
            LocalExecutionPlanner,
            PhysicalPlan,
        )
        from trino_tpu.runtime.session import SessionProperties
        from trino_tpu.telemetry import NULL_TRACER

        tracer = tracer if tracer is not None else NULL_TRACER
        catalogs = self.catalogs
        if desc.split_mod is not None:
            index, total = desc.split_mod
            catalogs = _FilteringCatalogs(self.catalogs, index, total)

        props = SessionProperties()
        for k, v in desc.properties.items():
            props.set(k, v)
        lp = LocalExecutionPlanner(
            catalogs, target_splits=props.get("target_splits"), properties=props
        )
        # coordinator-delivered dynamic filters fuse into this fragment's
        # scans exactly like locally-registered build ranges
        for name, rng in (desc.dynamic_ranges or {}).items():
            lp.dynamic_filters[name] = tuple(rng)
        saved = lp.plan

        def hook(node):
            if isinstance(node, RemoteSourceNode):
                batches = []
                # input pulls are the task's DCN wait: a distinct span per
                # remote source so the merged cross-host timeline separates
                # exchange stall from fragment compute
                with tracer.span(
                    "input_fetch", source_fragment=node.fragment_id
                ):
                    for url in desc.inputs.get(node.fragment_id, ()):
                        batches.extend(bytes_to_batches(_http_get(url)))
                return PhysicalPlan(iter(batches), node.symbols)
            return saved(node)

        lp.plan = hook
        from trino_tpu.runtime.lifecycle import check_current

        with tracer.span("execute_fragment", task_id=desc.task_id):
            out = lp.plan(desc.fragment_root)
            batches = []
            for b in out.stream:
                check_current()  # canceled/expired tasks abort between batches
                batches.append(b)
        if not batches:
            empty = [batches_to_bytes([])] * (
                desc.output_partitioning[1] if desc.output_partitioning else 1
            )
            return empty, {}
        ranges = (
            _result_ranges(batches, desc.output_symbols)
            if desc.collect_ranges
            else {}
        )
        if desc.output_partitioning is None:
            return [batches_to_bytes(batches)], ranges
        channels, n = desc.output_partitioning
        host = concat_batches(batches)
        import jax

        host = jax.device_get(host)
        buckets = partition_batches([host], channels, n)
        return [batches_to_bytes(bs) for bs in buckets], ranges


def _result_ranges(batches, symbols) -> dict:
    """{symbol name: [lo, hi]} over 1-D numeric result columns (the
    dynamic-filter summary; dictionary/limb-plane/bool columns skipped)."""
    import jax
    import numpy as np

    out: dict = {}
    for i, sym in enumerate(symbols):
        lo = hi = None
        for b in batches:
            c = b.columns[i]
            d = np.asarray(jax.device_get(c.data))
            if d.ndim != 1 or c.dictionary is not None or d.dtype == np.bool_:
                lo = None
                break
            if not np.issubdtype(d.dtype, np.number):
                lo = None
                break
            live = np.asarray(jax.device_get(b.mask()))
            if c.valid is not None:
                live = live & np.asarray(jax.device_get(c.valid))
            if not live.any():
                continue
            vals = d[live]
            blo, bhi = vals.min(), vals.max()
            lo = blo if lo is None else min(lo, blo)
            hi = bhi if hi is None else max(hi, bhi)
        if lo is not None and hi is not None:
            out[sym.name] = [int(lo), int(hi)] if np.issubdtype(
                type(lo), np.integer
            ) else [float(lo), float(hi)]
    return out


class _FilteringCatalogs:
    def __init__(self, inner, index: int, total: int):
        self._inner = inner
        self._index = index
        self._total = total

    def get(self, name: str):
        return _FilteringConnector(self._inner.get(name), self._index, self._total)

    def names(self):
        return self._inner.names()

    def register(self, name, connector):
        self._inner.register(name, connector)


def result_wait_default() -> float:
    """Long-poll bound on a task's result/dynamic endpoints when the
    descriptor carries no deadline (PR 5 moved the hardcoded 600 s into ONE
    place; the typed config now owns it: `worker.result-wait`)."""
    from trino_tpu.config import get_config

    return get_config().worker.result_wait_s


def status_wait_default() -> float:
    """Short status long-poll (reference: the async task-status responses;
    typed config `worker.status-wait`)."""
    from trino_tpu.config import get_config

    return get_config().worker.status_wait_s


def _result_wait_s(t: _Task) -> float:
    """Result long-poll bound: never wait on a task longer than its owning
    query has LEFT to live — the task lifecycle's remaining time, not the
    original budget (a late re-fetch after retries must not pin a server
    thread past the query's death)."""
    bound = result_wait_default()
    if t.desc.deadline_s is None:
        return bound
    rem = t.lifecycle.remaining_s()
    if rem is None:  # deadline_s <= 0: the owning query is out of time
        return 0.001
    return max(0.001, min(bound, rem))


def _http_get(url: str, timeout: Optional[float] = None) -> bytes:
    """Intra-cluster GET.  The timeout derives from the executing query's
    remaining run time (lifecycle.request_timeout) unless the caller passes
    an explicit bound — no HTTP call outlives its query."""
    from trino_tpu.runtime.lifecycle import request_timeout
    from trino_tpu.runtime.retry import FAILURE_INJECTOR

    # chaos hook for the pull data plane (result + input fetches).  Named
    # `fetch:` — NOT `http:` — so injection patterns don't accidentally
    # match the scheme inside every point's url suffix
    FAILURE_INJECTOR.maybe_fail(f"fetch:{url}")
    if timeout is None:
        timeout = request_timeout(result_wait_default())
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def main():  # pragma: no cover - manual entry point
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument(
        "--host",
        default="127.0.0.1",
        help="bind address; non-loopback requires TRINO_TPU_CLUSTER_SECRET",
    )
    args = ap.parse_args()
    w = WorkerServer(port=args.port, host=args.host)
    print(f"worker listening on {w.url}", flush=True)
    w._httpd.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
