"""Authentication + access control.

Reference roles: server/security/AuthenticationFilter.java (request
authentication), plugin/trino-password-file (PasswordAuthenticator), and
spi/security/SystemAccessControl + the file-based access control plugin
(plugin/trino-file-based-access-control rules: user/catalog/schema/table
patterns with privilege sets).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import re
from dataclasses import dataclass, field
from typing import Optional, Sequence


class AccessDeniedError(PermissionError):
    pass


class AuthenticationError(PermissionError):
    pass


# -- authentication (password-file plugin role) ------------------------------


class PasswordAuthenticator:
    """user -> salted-hash store; constant-time verification."""

    #: PBKDF2 rounds (reference password-file plugin uses bcrypt/PBKDF2;
    #: kept modest because tests create many users per run)
    ROUNDS = 50_000

    def __init__(self, users: Optional[dict] = None):
        #: user -> (random salt bytes, pbkdf2_hmac(sha256) digest)
        self._users: dict[str, tuple] = {}
        for user, password in (users or {}).items():
            self.set_password(user, password)

    def set_password(self, user: str, password: str) -> None:
        import os

        salt = os.urandom(16)
        digest = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), salt, self.ROUNDS
        )
        self._users[user] = (salt, digest)

    def authenticate(self, user: str, password: str) -> bool:
        entry = self._users.get(user)
        if entry is None:
            return False
        salt, expect = entry
        got = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, self.ROUNDS)
        return hmac.compare_digest(got, expect)

    @classmethod
    def from_file(cls, path: str) -> "PasswordAuthenticator":
        """password file: `user:password` lines (the password-file plugin's
        format, plaintext variant for tests)."""
        auth = cls()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                user, _, password = line.partition(":")
                auth.set_password(user, password)
        return auth

    def authenticate_basic(self, header: Optional[str]) -> str:
        """Authorization: Basic ... -> user, or raise."""
        if not header or not header.startswith("Basic "):
            raise AuthenticationError("missing basic credentials")
        try:
            raw = base64.b64decode(header[6:]).decode()
            user, _, password = raw.partition(":")
        except Exception as e:
            raise AuthenticationError("malformed basic credentials") from e
        if not self.authenticate(user, password):
            raise AuthenticationError(f"invalid credentials for {user}")
        return user


# -- access control (SystemAccessControl + file-based rules role) ------------


@dataclass(frozen=True)
class AccessRule:
    """One rule: patterns + allowed privileges, first match wins."""

    user: str = ".*"
    catalog: str = ".*"
    schema: str = ".*"
    table: str = ".*"
    privileges: tuple = ("SELECT", "INSERT", "UPDATE", "DELETE", "OWNERSHIP")

    def matches(self, user: str, catalog: str, schema: str, table: str) -> bool:
        return (
            re.fullmatch(self.user, user) is not None
            and re.fullmatch(self.catalog, catalog) is not None
            and re.fullmatch(self.schema, schema) is not None
            and re.fullmatch(self.table, table) is not None
        )


class AccessControl:
    """SPI (spi/security/SystemAccessControl)."""

    def check_can_execute_query(self, user: str) -> None:
        pass

    def check_can_select(
        self, user: str, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_write(
        self, user: str, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_delete(
        self, user: str, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def check_can_update(
        self, user: str, catalog: str, schema: str, table: str
    ) -> None:
        pass

    def filter_catalogs(self, user: str, catalogs: Sequence[str]) -> list:
        return list(catalogs)


class AllowAllAccessControl(AccessControl):
    pass


class GrantManager:
    """SQL-standard grants + roles store (reference roles:
    spi/security/Privilege.java, MetadataManager.grantTablePrivileges, and
    plugin/trino-hive SqlStandardAccessControl's grant model).

    Grants are keyed by principal (user or role); role membership is
    transitive (roles may be granted to roles)."""

    PRIVILEGES = ("SELECT", "INSERT", "UPDATE", "DELETE", "OWNERSHIP")

    def __init__(self):
        #: (principal, catalog, schema, table) -> set of privileges
        self._grants: dict[tuple, set] = {}
        #: role -> set of member principals (users or roles)
        self._roles: dict[str, set] = {}
        #: (catalog, schema, table) -> owner user
        self._owners: dict[tuple, str] = {}

    # -- roles ---------------------------------------------------------------

    def create_role(self, role: str) -> None:
        if role in self._roles:
            raise ValueError(f"role {role!r} already exists")
        self._roles[role] = set()

    def drop_role(self, role: str) -> None:
        if role not in self._roles:
            raise ValueError(f"role {role!r} does not exist")
        del self._roles[role]
        for members in self._roles.values():
            members.discard(role)

    def list_roles(self) -> list:
        return sorted(self._roles)

    def grant_role(self, role: str, principal: str) -> None:
        if role not in self._roles:
            raise ValueError(f"role {role!r} does not exist")
        self._roles[role].add(principal)

    def revoke_role(self, role: str, principal: str) -> None:
        if role not in self._roles:
            raise ValueError(f"role {role!r} does not exist")
        self._roles[role].discard(principal)

    def principals_of(self, user: str) -> set:
        """user + every role reachable through membership (transitive)."""
        out = {user}
        changed = True
        while changed:
            changed = False
            for role, members in self._roles.items():
                if role not in out and members & out:
                    out.add(role)
                    changed = True
        return out

    # -- privileges ----------------------------------------------------------

    def grant(self, principal, privileges, catalog, schema, table) -> None:
        key = (principal, catalog, schema, table)
        st = self._grants.setdefault(key, set())
        for p in privileges:
            p = p.upper()
            if p not in self.PRIVILEGES and p != "ALL":
                raise ValueError(f"unknown privilege {p}")
            if p == "ALL":
                st.update(self.PRIVILEGES)
            else:
                st.add(p)

    def revoke(self, principal, privileges, catalog, schema, table) -> None:
        key = (principal, catalog, schema, table)
        st = self._grants.get(key)
        if st is None:
            return
        for p in privileges:
            p = p.upper()
            if p == "ALL":
                st.clear()
            else:
                st.discard(p)
        if not st:
            del self._grants[key]

    def set_owner(self, catalog, schema, table, user) -> None:
        self._owners[(catalog, schema, table)] = user

    def has_privilege(self, user, priv, catalog, schema, table) -> bool:
        if self._owners.get((catalog, schema, table)) == user:
            return True
        principals = self.principals_of(user)
        for p in principals:
            st = self._grants.get((p, catalog, schema, table))
            if st and (priv in st or "OWNERSHIP" in st):
                return True
        return False

    def grants_for(self, catalog=None, schema=None, table=None) -> list:
        """(grantee, privilege, catalog, schema, table) rows for SHOW GRANTS."""
        out = []
        for (p, c, s, t), privs in sorted(self._grants.items()):
            if catalog is not None and (c, s, t) != (catalog, schema, table):
                continue
            for pr in sorted(privs):
                out.append((p, pr, c, s, t))
        return out


class SqlStandardAccessControl(AccessControl):
    """GRANT-driven enforcement (reference: trino-hive SqlStandardAccessControl
    semantics: owner or granted privilege required; `admin` bypasses)."""

    def __init__(self, grants: GrantManager, admin: str = "admin"):
        self.grants = grants
        self.admin = admin

    def _check(self, priv, user, catalog, schema, table) -> None:
        if user == self.admin:
            return
        if not self.grants.has_privilege(user, priv, catalog, schema, table):
            raise AccessDeniedError(
                f"user {user} lacks {priv} on {catalog}.{schema}.{table}"
            )

    def check_can_select(self, user, catalog, schema, table) -> None:
        self._check("SELECT", user, catalog, schema, table)

    def check_can_write(self, user, catalog, schema, table) -> None:
        self._check("INSERT", user, catalog, schema, table)

    def check_can_delete(self, user, catalog, schema, table) -> None:
        self._check("DELETE", user, catalog, schema, table)

    def check_can_update(self, user, catalog, schema, table) -> None:
        self._check("UPDATE", user, catalog, schema, table)


class RuleBasedAccessControl(AccessControl):
    """File-based access control semantics: first matching rule decides;
    no matching rule denies."""

    def __init__(self, rules: Sequence[AccessRule], query_users: str = ".*"):
        self.rules = list(rules)
        self.query_users = query_users

    @classmethod
    def from_dicts(cls, rules: Sequence[dict], **kw) -> "RuleBasedAccessControl":
        return cls(
            [
                AccessRule(
                    user=r.get("user", ".*"),
                    catalog=r.get("catalog", ".*"),
                    schema=r.get("schema", ".*"),
                    table=r.get("table", ".*"),
                    privileges=tuple(
                        p.upper() for p in r.get("privileges", ())
                    ),
                )
                for r in rules
            ],
            **kw,
        )

    def check_can_execute_query(self, user: str) -> None:
        if re.fullmatch(self.query_users, user) is None:
            raise AccessDeniedError(f"user {user} cannot execute queries")

    def _check(self, priv, user, catalog, schema, table) -> None:
        for rule in self.rules:
            if rule.matches(user, catalog, schema, table):
                if priv in rule.privileges:
                    return
                break  # first match decides
        raise AccessDeniedError(
            f"user {user} lacks {priv} on {catalog}.{schema}.{table}"
        )

    def check_can_select(self, user, catalog, schema, table) -> None:
        self._check("SELECT", user, catalog, schema, table)

    def check_can_write(self, user, catalog, schema, table) -> None:
        self._check("INSERT", user, catalog, schema, table)

    def check_can_delete(self, user, catalog, schema, table) -> None:
        self._check("DELETE", user, catalog, schema, table)

    def check_can_update(self, user, catalog, schema, table) -> None:
        self._check("UPDATE", user, catalog, schema, table)

    def filter_catalogs(self, user: str, catalogs: Sequence[str]) -> list:
        """First-match-wins (like _check): the FIRST rule matching
        user+catalog decides visibility, and only if it grants at least one
        privilege — a privilege-less rule must not reveal the catalog."""
        out = []
        for c in catalogs:
            for r in self.rules:
                if re.fullmatch(r.user, user) and re.fullmatch(r.catalog, c):
                    if r.privileges:
                        out.append(c)
                    break  # first match decides
        return out
