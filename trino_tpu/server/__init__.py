"""Coordinator HTTP server + client protocol.

Reference layer: core/trino-main/.../server + server/protocol — the
`/v1/statement` REST protocol (QueuedStatementResource.java:102,
ExecutingStatementResource.java:73): POST submits SQL, the client follows
`nextUri` long-polls until FINISHED, receiving paged JSON rows.
"""

from trino_tpu.server.coordinator import CoordinatorServer
