"""Coordinator HTTP server.

Reference roles: dispatcher/QueuedStatementResource.java:157 (POST
/v1/statement), server/protocol/ExecutingStatementResource.java:73 (paged
GET), DispatchManager (query registry/lifecycle), QueryStateMachine states
QUEUED -> RUNNING -> FINISHED/FAILED (execution/QueryState.java:26-58).

Implementation: stdlib ThreadingHTTPServer; each query runs on a worker
thread against the shared LocalQueryRunner (execution itself fans out on the
device); results are paged back RESULT_PAGE_ROWS at a time via nextUri
tokens, and a client that stops following nextUri leaves the query to a
DELETE (cancel) or the finished-result GC, like the reference's token-acked
paging.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from trino_tpu.server import protocol

def result_page_rows() -> int:
    """Rows per paged statement response (typed config
    coordinator.result-page-rows; compiled-in default 4096)."""
    from trino_tpu.config import get_config

    return get_config().coordinator.result_page_rows


def poll_wait_s() -> float:
    """Long-poll bound on statement/trace GETs (reference: the async
    responses; typed config coordinator.poll-wait)."""
    from trino_tpu.config import get_config

    return get_config().coordinator.poll_wait_s


class _Query:
    def __init__(self, qid: str, sql: str):
        self.id = qid
        self.sql = sql
        self.state = "QUEUED"
        self.result = None
        self.error: Optional[dict] = None
        #: Chrome-trace/Perfetto JSON captured at completion (query_trace)
        self.trace: Optional[dict] = None
        self.done = threading.Event()
        self._lock = threading.Lock()
        #: runtime lifecycle handle, attached the moment the engine creates
        #: it (LocalQueryRunner._query_context_cb); DELETE resolves here
        self.lifecycle = None
        #: dispatcher admission ticket (runtime/dispatcher): DELETE on a
        #: still-queued query dequeues it here, without ever acquiring an
        #: admission slot or engine time
        self.ticket = None
        #: cancel arrived before execution started (cancel-while-queued)
        self.cancel_requested = False

    def cancel(self) -> None:
        """DELETE /v1/query/{id}: a REAL cancel — the running statement
        aborts at its next cooperative check and fans the cancel out to its
        remote tasks; a queued one dequeues before it starts."""
        with self._lock:
            self.cancel_requested = True
            ctx = self.lifecycle
            ticket = self.ticket
        if ticket is not None:
            ticket.cancel()
        if ctx is not None:
            ctx.cancel("canceled via DELETE /v1/query")

    def _attach(self, ctx) -> None:
        with self._lock:
            self.lifecycle = ctx
            pre = self.cancel_requested
        if pre:
            ctx.cancel("canceled via DELETE /v1/query")

    def _attach_ticket(self, ticket) -> None:
        with self._lock:
            self.ticket = ticket
            pre = self.cancel_requested
        if pre:
            ticket.cancel()

    def run(self, runner) -> None:
        from trino_tpu.runtime.lifecycle import QueryCanceledException

        self.state = "RUNNING"
        runner._query_context_cb = self._attach
        try:
            self.result = runner.execute(self.sql)
            self.state = "FINISHED"
        except Exception as e:  # surface as protocol error object
            from trino_tpu.runtime.events import classify_error

            self.state = (
                "CANCELED" if isinstance(e, QueryCanceledException) else "FAILED"
            )
            self.error = {
                "message": str(e),
                "errorName": type(e).__name__,
                "errorType": classify_error(e),
                "errorCode": getattr(e, "error_code", None),
                "stack": traceback.format_exc(),
            }
        finally:
            # execute can raise BEFORE consuming the one-shot callback
            # (parse/access-control errors): clear it so a later statement
            # never attaches ITS context to this dead query's cancel surface
            runner._query_context_cb = None
            # span trace of THIS query (GET /v1/query/{id}/trace): read
            # from the statement's OWN lifecycle context, so a neighboring
            # lane finishing first can never hand us its trace (the
            # pre-dispatcher code diffed the shared runner.last_trace,
            # which raced under concurrent lanes)
            with self._lock:
                ctx = self.lifecycle
            self.trace = getattr(ctx, "trace_json", None)
            self.done.set()

    def columns_json(self) -> list:
        r = self.result
        return [
            {"name": n, "type": (t.name if t is not None else "unknown")}
            for n, t in zip(r.column_names, r.types or [None] * len(r.column_names))
        ]


class CoordinatorServer:
    """serve() blocks; start()/shutdown() for embedded use (tests, CLI)."""

    def __init__(
        self,
        runner=None,
        host: str = "127.0.0.1",
        port: int = 8080,
        resource_groups=None,
        authenticator=None,
        access_control=None,
        dispatcher=None,
    ):
        from trino_tpu.config import get_config
        from trino_tpu.runtime.dispatcher import QueryDispatcher
        from trino_tpu.runtime.resource_groups import ResourceGroupManager
        from trino_tpu.runtime.runner import LocalQueryRunner

        self.runner = runner or LocalQueryRunner()
        #: optional PasswordAuthenticator (AuthenticationFilter role)
        self.authenticator = authenticator
        if access_control is not None:
            self.runner.access_control = access_control
        self.host = host
        self.port = port
        self._queries: dict[str, _Query] = {}
        self._qid = itertools.count(1)
        #: admission control (resource-group tree): the engine/device is the
        #: shared resource, hard_concurrency bounds concurrent executions
        #: (reference: InternalResourceGroupManager); group definitions load
        #: from `resource-groups.*` config properties when no manager is
        #: passed in
        if resource_groups is None:
            props = get_config().properties
            resource_groups = ResourceGroupManager.from_properties(props)
            if not any(
                k.startswith("resource-groups.global.") for k in props
            ):
                # unconfigured default: let the global group use every
                # engine lane (the pre-dispatcher default of 1 modeled the
                # old global engine lock, which is gone)
                resource_groups.default.config.hard_concurrency = max(
                    1, int(get_config().dispatcher.lanes)
                )
        self.resource_groups = resource_groups
        # query performance observatory: the profile archive must attach
        # BEFORE the dispatcher clones its engine lanes — lanes copy the
        # runner's store reference at clone time, so a start()-time attach
        # would leave lanes 1..N-1 storeless and silently skip archiving
        # (N-1)/N of served queries.  Idempotent no-op when
        # profile.archive-dir is unset or a store is already attached.
        from trino_tpu.telemetry.profile_store import attach_profile_store

        attach_profile_store(self.runner)
        #: the concurrent dispatcher (runtime/dispatcher): replaces the old
        #: global engine lock — statements admit through weighted-fair
        #: resource groups onto engine lanes, overload sheds, queued time
        #: is bounded, and drain is graceful
        self.dispatcher = dispatcher or QueryDispatcher(
            self.runner, self.resource_groups
        )
        #: SQL surface: system.runtime.resource_groups reads live admission
        #: state through the runner binding
        self.runner.dispatcher = self.dispatcher
        self._httpd: Optional[ThreadingHTTPServer] = None
        self.started_at = time.monotonic()
        #: True when start() launched the runner's heartbeat failure
        #: detector (so shutdown() knows to stop it — PR 7 gap (a): the
        #: coordinator owns the probe loop, callers no longer opt in)
        self._detector_started = False

    # -- query lifecycle ------------------------------------------------------

    def submit(self, sql: str, user: Optional[str] = None) -> _Query:
        from trino_tpu.runtime.dispatcher import (
            DispatcherStoppedError,
            QueryShedError,
        )
        from trino_tpu.runtime.lifecycle import (
            QueryCanceledException,
            QueryQueuedTimeExceeded,
        )

        q = _Query(f"q_{next(self._qid)}", sql)
        self._queries[q.id] = q

        def fail(exc, name: str, etype: str, **extra) -> None:
            q.state = "FAILED"
            q.error = {
                "message": str(exc),
                "errorName": name,
                "errorType": etype,
                "errorCode": getattr(exc, "error_code", name),
                **extra,
            }
            q.done.set()

        def work():
            try:
                ticket = self.dispatcher.enqueue(user=user)
            except QueryShedError as e:
                fail(
                    e, "QUERY_QUEUE_FULL", "RESOURCE_ERROR",
                    retryable=True, retryAfterSeconds=e.retry_after_s,
                )
                return
            except DispatcherStoppedError as e:
                fail(e, "SERVER_SHUTTING_DOWN", "RESOURCE_ERROR")
                return
            ticket.on_force_kill = q.cancel
            q._attach_ticket(ticket)
            try:
                ticket.wait()
            except QueryCanceledException:
                # canceled while queued: never occupied the engine
                q.state = "CANCELED"
                q.error = {
                    "message": "canceled via DELETE /v1/query",
                    "errorName": "USER_CANCELED",
                    "errorType": "USER_ERROR",
                    "errorCode": "USER_CANCELED",
                }
                q.done.set()
                return
            except QueryQueuedTimeExceeded as e:
                fail(e, "EXCEEDED_QUEUED_TIME_LIMIT", "RESOURCE_ERROR")
                return
            except DispatcherStoppedError as e:
                fail(e, "SERVER_SHUTTING_DOWN", "RESOURCE_ERROR")
                return

            def run(lane_runner):
                # statement identity: a lane runs one statement at a time,
                # so the per-statement user is race-free
                lane_runner.user = user or "user"
                q.run(lane_runner)

            try:
                self.dispatcher.run_admitted(ticket, run)
            except QueryCanceledException:
                # cancel won the race against admission: slot handed back,
                # no engine time consumed
                q.state = "CANCELED"
                q.error = {
                    "message": "canceled via DELETE /v1/query",
                    "errorName": "USER_CANCELED",
                    "errorType": "USER_ERROR",
                    "errorCode": "USER_CANCELED",
                }
                q.done.set()
                return
            # successful SELECTs feed the prewarm replay set: the
            # manifest a restarted server replays IS the live workload
            pw = getattr(self.runner, "prewarm", None)
            if pw is not None and q.state == "FINISHED":
                pw.record(q.sql)

        threading.Thread(
            target=work, daemon=True, name=f"statement-{q.id}"
        ).start()
        return q

    def query(self, qid: str) -> Optional[_Query]:
        return self._queries.get(qid)

    # -- HTTP -----------------------------------------------------------------

    def _handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # silence default stderr noise
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                from trino_tpu.server.security import AuthenticationError

                if self.path != "/v1/statement":
                    return self._send(404, {"error": {"message": "not found"}})
                try:
                    auth_user = self._authenticate()
                except AuthenticationError:
                    return
                user = auth_user or self.headers.get("X-Trino-User")
                # load shedding BEFORE the body is read (reference:
                # DispatchManager queue-full rejection): a full resource-
                # group queue answers 429 + Retry-After without touching
                # the statement text, so overload costs the coordinator a
                # header parse, not a body read + parse + thread
                shed_after = server.dispatcher.shed_probe(user)
                if shed_after is not None:
                    self.close_connection = True  # body intentionally unread
                    body = json.dumps(
                        {
                            "error": {
                                "message": (
                                    "resource group queue is full; retry "
                                    f"after {shed_after:.1f}s"
                                ),
                                "errorName": "QUERY_QUEUE_FULL",
                                "errorType": "RESOURCE_ERROR",
                                "errorCode": "QUERY_QUEUE_FULL",
                                "retryable": True,
                            }
                        }
                    ).encode()
                    self.send_response(429)
                    self.send_header(
                        "Retry-After", str(max(1, int(shed_after + 0.999)))
                    )
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(body)
                    return
                n = int(self.headers.get("Content-Length", 0))
                sql = self.rfile.read(n).decode()
                q = server.submit(sql, user=user)
                self._send(
                    200,
                    protocol.query_results(
                        q.id,
                        next_uri=f"/v1/statement/executing/{q.id}/0",
                        state=q.state,
                    ),
                )

            def _authenticate(self):
                """When an authenticator is configured, EVERY request needs
                credentials — result paging and the UI expose query text and
                data, not just statement submission."""
                if server.authenticator is None:
                    return None
                from trino_tpu.server.security import AuthenticationError

                try:
                    return server.authenticator.authenticate_basic(
                        self.headers.get("Authorization")
                    )
                except AuthenticationError as e:
                    self._send(
                        401,
                        {
                            "error": {
                                "message": str(e),
                                "errorName": "AUTHENTICATION_FAILED",
                            }
                        },
                    )
                    raise

            def do_GET(self):
                from trino_tpu.server.security import AuthenticationError

                try:
                    self._authenticate()
                except AuthenticationError:
                    return
                if self.path.startswith("/ui"):
                    from trino_tpu.server.ui import handle_ui_get

                    out = handle_ui_get(server, self.path)
                    if out is not None:
                        status, ctype, body = out
                        self.send_response(status)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                if self.path == "/v1/metrics":
                    # Prometheus text exposition (telemetry/metrics)
                    from trino_tpu.telemetry import REGISTRY

                    body = REGISTRY.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # /v1/dictionary/{catalog}/{schema}/{table}/{column}
                # ?version=N — versioned global code assignment fetch
                # (runtime/dictionary_service): a worker holding a
                # `("ref", key, version)` wire dictionary it cannot resolve
                # locally pulls the exact recorded version from the
                # coordinator, never a "close enough" one
                if self.path.split("?", 1)[0].startswith("/v1/dictionary/"):
                    from urllib.parse import parse_qs, urlsplit

                    from trino_tpu.runtime.dictionary_service import (
                        DICTIONARY_SERVICE,
                    )

                    u = urlsplit(self.path)
                    dparts = u.path.strip("/").split("/")
                    if len(dparts) != 6:
                        return self._send(
                            404, {"error": {"message": "not found"}}
                        )
                    key = tuple(dparts[2:6])
                    qs = parse_qs(u.query)
                    try:
                        version = int(qs.get("version", ["0"])[0])
                    except ValueError:
                        return self._send(
                            400, {"error": {"message": "bad version"}}
                        )
                    try:
                        entry = DICTIONARY_SERVICE.entry(key, version)
                    except KeyError:
                        return self._send(
                            404,
                            {
                                "error": {
                                    "message": "no such dictionary version"
                                }
                            },
                        )
                    from trino_tpu.columnar.dictionary import (
                        UnorderedDictionary,
                    )

                    return self._send(
                        200,
                        {
                            "key": list(key),
                            "version": entry.version,
                            "values": list(entry.dictionary.values),
                            "ordered": not isinstance(
                                entry.dictionary, UnorderedDictionary
                            ),
                            "unique": entry.unique,
                        },
                    )
                parts = self.path.strip("/").split("/")
                # /v1/query/{id}/profile — the archived profile artifact
                # (telemetry/profile_store): accepts the coordinator's
                # q_N id (resolved to the engine query id through the
                # attached lifecycle) or an engine query_N / artifact key
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "query"]
                    and parts[3] == "profile"
                ):
                    store = getattr(server.runner, "profile_store", None)
                    if store is None:
                        return self._send(
                            404,
                            {
                                "error": {
                                    "message": "profile archive not "
                                    "configured (set profile.archive-dir "
                                    "or attach a ProfileStore)"
                                }
                            },
                        )
                    lookup = parts[2]
                    q = server.query(lookup)
                    if q is not None:
                        q.done.wait(timeout=poll_wait_s())
                        if not q.done.is_set():
                            # a KNOWN still-running query must answer
                            # "not yet" — falling through to the disk
                            # scan could serve a PREVIOUS incarnation's
                            # artifact under the same engine query_N id
                            return self._send(
                                404,
                                {
                                    "error": {
                                        "message": "no archived profile "
                                        "yet (query still running)"
                                    }
                                },
                            )
                        ctx = q.lifecycle
                        if ctx is not None:
                            lookup = ctx.query_id
                    art = store.get(lookup)
                    if art is None:
                        return self._send(
                            404,
                            {
                                "error": {
                                    "message": "no archived profile for "
                                    "this query (still running, or the "
                                    "artifact was pruned)"
                                }
                            },
                        )
                    return self._send(200, art)
                # /v1/query/{id}/decisions — the plan-decision ledger
                # (telemetry/decisions) out of the archived profile
                # artifact: what the planner chose, what it cost, and the
                # hindsight verdicts; same id resolution as /profile
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "query"]
                    and parts[3] == "decisions"
                ):
                    store = getattr(server.runner, "profile_store", None)
                    if store is None:
                        return self._send(
                            404,
                            {
                                "error": {
                                    "message": "profile archive not "
                                    "configured (set profile.archive-dir "
                                    "or attach a ProfileStore)"
                                }
                            },
                        )
                    lookup = parts[2]
                    q = server.query(lookup)
                    if q is not None:
                        q.done.wait(timeout=poll_wait_s())
                        if not q.done.is_set():
                            return self._send(
                                404,
                                {
                                    "error": {
                                        "message": "no decision ledger "
                                        "yet (query still running)"
                                    }
                                },
                            )
                        ctx = q.lifecycle
                        if ctx is not None:
                            lookup = ctx.query_id
                    art = store.get(lookup)
                    if art is None or art.get("decisions") is None:
                        return self._send(
                            404,
                            {
                                "error": {
                                    "message": "no decision ledger for "
                                    "this query (still running, or the "
                                    "artifact was pruned)"
                                }
                            },
                        )
                    return self._send(200, art["decisions"])
                # /v1/query/{id}/trace — Perfetto/Chrome-trace JSON
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "query"]
                    and parts[3] == "trace"
                ):
                    q = server.query(parts[2])
                    if q is None:
                        return self._send(
                            404, {"error": {"message": "no such query"}}
                        )
                    q.done.wait(timeout=poll_wait_s())
                    if q.trace is None:
                        return self._send(
                            404,
                            {
                                "error": {
                                    "message": "no trace for this query "
                                    "(still running, or query_trace off)"
                                }
                            },
                        )
                    return self._send(200, q.trace)
                # /v1/statement/executing/{id}/{token}
                if len(parts) != 5 or parts[:3] != ["v1", "statement", "executing"]:
                    return self._send(404, {"error": {"message": "not found"}})
                qid, token = parts[3], int(parts[4])
                q = server.query(qid)
                if q is None:
                    return self._send(404, {"error": {"message": "no such query"}})
                # long-poll like the reference's async responses
                q.done.wait(timeout=poll_wait_s())
                if q.state in ("FAILED", "CANCELED"):
                    return self._send(
                        200,
                        protocol.query_results(q.id, state=q.state, error=q.error),
                    )
                if not q.done.is_set():
                    return self._send(
                        200,
                        protocol.query_results(
                            q.id,
                            next_uri=f"/v1/statement/executing/{qid}/{token}",
                            state=q.state,
                        ),
                    )
                rows = q.result.rows
                page_sz = result_page_rows()
                page = rows[token * page_sz : (token + 1) * page_sz]
                has_more = (token + 1) * page_sz < len(rows)
                self._send(
                    200,
                    protocol.query_results(
                        q.id,
                        columns=q.columns_json(),
                        data=protocol.encode_rows(page),
                        next_uri=(
                            f"/v1/statement/executing/{qid}/{token + 1}"
                            if has_more
                            else None
                        ),
                        state="FINISHED",
                        stats={"rows": len(rows)},
                    ),
                )

            def do_PUT(self):
                from trino_tpu.server.security import AuthenticationError

                try:
                    self._authenticate()
                except AuthenticationError:
                    return
                if self.path not in (
                    "/v1/worker/register", "/v1/worker/drain"
                ):
                    return self._send(
                        404, {"error": {"message": "not found"}}
                    )
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                # membership mutation is as privileged as task submission:
                # when the cluster secret is set, register/drain need the
                # intra-cluster HMAC (an unauthenticated PUT would let any
                # peer grow or shrink the mesh) — the same gate the
                # worker's own /v1/worker/shutdown enforces
                from trino_tpu.server.worker import cluster_secret, sign_body

                secret = cluster_secret()
                if secret is not None:
                    import hmac as _hmac

                    sig = self.headers.get("X-Cluster-Auth", "")
                    if not _hmac.compare_digest(
                        sig, sign_body(secret, body)
                    ):
                        return self._send(
                            401, {"error": {"message": "bad signature"}}
                        )
                url = body.decode().strip()
                if self.path == "/v1/worker/register":
                    # the grow path (reference: DiscoveryNodeManager
                    # announcement): body = worker url; it joins the NEXT
                    # query's mesh, never a running one.  A restarted
                    # worker announces itself here (auto-rejoin).
                    add = getattr(server.runner, "add_worker", None)
                    if not url or add is None:
                        return self._send(
                            400,
                            {"error": {"message": "runner is not multi-host "
                                       "or no worker url given"}},
                        )
                    add(url)
                    return self._send(200, {"registered": url})
                # PUT /v1/worker/drain — graceful retirement: body = worker
                # url; the worker finishes running tasks, refuses new ones,
                # exits, and the next query's mesh excludes it
                drain = getattr(server.runner, "drain_worker", None)
                if not url or drain is None:
                    return self._send(
                        400,
                        {"error": {"message": "runner is not multi-host "
                                   "or no worker url given"}},
                    )
                drain(url)
                return self._send(200, {"draining": url})

            def do_DELETE(self):
                from trino_tpu.server.security import AuthenticationError

                try:
                    self._authenticate()
                except AuthenticationError:
                    return
                parts = self.path.strip("/").split("/")
                # DELETE /v1/query/{id} — a REAL cancel (reference:
                # QueuedStatementResource cancel): the running statement
                # aborts at its next cooperative check, remote tasks get
                # their cancel fan-out, and the query shows CANCELED
                if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                    q = server.query(parts[2])
                    if q is None:
                        return self._send(
                            404, {"error": {"message": "no such query"}}
                        )
                    q.cancel()
                    return self._send(204, {})
                if len(parts) >= 4 and parts[:3] == ["v1", "statement", "executing"]:
                    q = server._queries.pop(parts[3], None)
                    if q is not None:
                        q.cancel()  # abandoning the result cancels the query
                    return self._send(204, {})
                self._send(404, {"error": {"message": "not found"}})

        return Handler

    def start(self) -> None:
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._handler())
        self.port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="coordinator-http",
        ).start()
        self._start_background()

    def _start_background(self) -> None:
        """Server-owned background services (started with the listener,
        stopped by shutdown()):

        * the runner's heartbeat failure detector probe loop — PR 7 left
          `HeartbeatDetector.start()` to callers; the server is the only
          process that should own it (only membership-backed detectors
          have a start/stop loop — the in-mesh detector refreshes at query
          start and needs none);
        * the prewarm executor (runtime/prewarm): attach one from
          `prewarm.manifest-path` when the runner has none, and replay the
          persisted workload manifest in the background so restart cost is
          paid before the first query, not by it."""
        det = getattr(self.runner, "failure_detector", None)
        if det is not None and callable(getattr(det, "start", None)) \
                and callable(getattr(det, "stop", None)):
            det.start()
            self._detector_started = True
        # the JSONL audit log attaches here when configured (idempotent
        # no-op without audit.log-path; the event pipeline is SHARED
        # across lanes, so unlike the profile store this can attach after
        # the dispatcher cloned them)
        from trino_tpu.telemetry.audit import attach_audit_log

        attach_audit_log(self.runner)
        from trino_tpu.config import get_config

        pw = getattr(self.runner, "prewarm", None)
        if pw is None and get_config().prewarm.manifest_path:
            from trino_tpu.runtime.prewarm import attach_prewarm

            pw = attach_prewarm(self.runner)
        if pw is not None:
            # adopt even a pre-attached executor (runner_from_etc creates
            # one with a private lock): replays — start AND later grow
            # kicks — admit through the dispatcher's weight-capped
            # system.prewarm resource group, so a replay waits its fair
            # turn on the primary lane and can never starve live user
            # queries the way the old engine-lock adoption could
            pw.use_admission(self.dispatcher.system_admission)
            if get_config().prewarm.on_start:
                pw.run(reason="start")

    def shutdown(self) -> None:
        # graceful dispatcher drain FIRST: admission closes, queued
        # statements fail classified (SERVER_SHUTTING_DOWN), running ones
        # finish inside dispatcher.drain-wait or are force-killed through
        # their lifecycle tokens (the PR 8 bounded force-kill contract)
        try:
            self.dispatcher.drain()
        except Exception:
            pass
        if self._detector_started:
            det = getattr(self.runner, "failure_detector", None)
            if det is not None:
                det.stop()
            self._detector_started = False
        pw = getattr(self.runner, "prewarm", None)
        if pw is not None:
            try:
                # the replay set observed this incarnation persists for the
                # next one (no-op without a manifest location / statements)
                pw.save()
            except Exception:
                pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

    def serve(self) -> None:
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._handler())
        print(f"trino-tpu coordinator listening on {self.host}:{self.port}")
        self._start_background()
        self._httpd.serve_forever()
