"""Coordinator reverse proxy.

Reference role: client/trino-proxy (ProxyResource.java — forwards
/v1/statement and nextUri traffic to a backing coordinator, rewriting the
URIs in responses so clients keep talking to the proxy).
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class ProxyServer:
    """HTTP proxy in front of a coordinator: POST /v1/statement and GET
    nextUri pages pass through; URIs in the JSON are rewritten to point at
    the proxy."""

    def __init__(self, backend_url: str, port: int = 0):
        self.backend_url = backend_url.rstrip("/")
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                proxy._forward(self, "POST", self.path, body)

            def do_GET(self):
                proxy._forward(self, "GET", self.path, None)

            def do_DELETE(self):
                proxy._forward(self, "DELETE", self.path, None)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "ProxyServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="proxy"
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- forwarding ----------------------------------------------------------

    def _forward(self, handler, method: str, path: str, body) -> None:
        req = urllib.request.Request(
            self.backend_url + path, data=body, method=method
        )
        for h in ("Content-Type", "X-Trino-User", "X-Trino-Session"):
            v = handler.headers.get(h)
            if v:
                req.add_header(h, v)
        from trino_tpu.runtime.lifecycle import DEFAULT_HTTP_TIMEOUT_S

        try:
            with urllib.request.urlopen(
                req, timeout=DEFAULT_HTTP_TIMEOUT_S
            ) as resp:
                payload = resp.read()
                status = resp.status
                ctype = resp.headers.get("Content-Type", "application/json")
        except urllib.error.HTTPError as e:
            payload = e.read()
            status = e.code
            ctype = e.headers.get("Content-Type", "application/json")
        except Exception as e:  # backend down
            payload = json.dumps({"error": str(e)}).encode()
            status = 502
            ctype = "application/json"
        payload = self._rewrite(payload)
        handler.send_response(status)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(payload)))
        handler.end_headers()
        handler.wfile.write(payload)

    def _rewrite(self, payload: bytes) -> bytes:
        """Point nextUri/infoUri at the proxy (ProxyResource's URI rewrite)."""
        try:
            doc = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            return payload
        changed = self._rewrite_uris(doc)
        return json.dumps(doc).encode() if changed else payload

    def _rewrite_uris(self, doc) -> bool:
        changed = False
        if isinstance(doc, dict):
            for key, val in doc.items():
                if (
                    key in ("nextUri", "infoUri", "partialCancelUri")
                    and isinstance(val, str)
                    and val.startswith(self.backend_url)
                ):
                    doc[key] = self.url + val[len(self.backend_url):]
                    changed = True
                else:
                    changed |= self._rewrite_uris(val)
        elif isinstance(doc, list):
            for item in doc:
                changed |= self._rewrite_uris(item)
        return changed
