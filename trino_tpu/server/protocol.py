"""Statement protocol: JSON wire shapes + value serde.

Reference: client/trino-client's QueryResults JSON (id, columns, data,
nextUri, stats, error) as produced by server/protocol/Query.java; values are
JSON-encoded per type exactly enough for the bundled client/CLI to round-trip
(decimals as strings, dates/timestamps ISO, varbinary hex).
"""

from __future__ import annotations

import datetime
from decimal import Decimal
from typing import Any, Optional, Sequence

from trino_tpu import types as T


def encode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, Decimal):
        return str(v)
    if isinstance(v, datetime.datetime):
        return v.isoformat(sep=" ")
    if isinstance(v, datetime.date):
        return v.isoformat()
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).hex()
    return str(v)


def decode_value(v: Any, type_name: str) -> Any:
    if v is None:
        return None
    if type_name.startswith("decimal"):
        return Decimal(v)
    if type_name == "date":
        return datetime.date.fromisoformat(v)
    if type_name == "timestamp":
        return datetime.datetime.fromisoformat(v)
    if type_name == "varbinary":
        return bytes.fromhex(v)
    return v


def encode_rows(rows: Sequence[Sequence]) -> list:
    return [[encode_value(v) for v in r] for r in rows]


def decode_rows(rows: Sequence[Sequence], columns: Sequence[dict]) -> list:
    names = [c["type"] for c in columns]
    return [
        tuple(decode_value(v, t) for v, t in zip(r, names)) for r in rows
    ]


def query_results(
    query_id: str,
    *,
    columns: Optional[list] = None,
    data: Optional[list] = None,
    next_uri: Optional[str] = None,
    state: str = "RUNNING",
    error: Optional[dict] = None,
    stats: Optional[dict] = None,
) -> dict:
    out = {
        "id": query_id,
        "stats": {"state": state, **(stats or {})},
    }
    if columns is not None:
        out["columns"] = columns
    if data is not None:
        out["data"] = data
    if next_uri is not None:
        out["nextUri"] = next_uri
    if error is not None:
        out["error"] = error
    return out
