"""Web UI: cluster overview + query list served by the coordinator.

Reference role: core/trino-main webapp (the /ui React app) + ClusterStatsResource
/ QueryResource JSON endpoints.  A single self-contained HTML page (no build
step, no external assets) polls the JSON endpoints the same way the
reference's UI polls /ui/api/stats and /ui/api/query.
"""

from __future__ import annotations

import json
import time


def handle_ui_get(server, path: str):
    """Route /ui requests.  Returns (status, content_type, body-bytes) or
    None when the path is not a UI path."""
    if path in ("/ui", "/ui/"):
        return 200, "text/html; charset=utf-8", _PAGE.encode()
    if path == "/ui/api/stats":
        return 200, "application/json", json.dumps(_stats(server)).encode()
    if path == "/ui/api/query":
        return 200, "application/json", json.dumps(_queries(server)).encode()
    if path.startswith("/ui/api/query/"):
        qid = path.rsplit("/", 1)[-1]
        q = server.query(qid)
        if q is None:
            return 404, "application/json", b'{"error": "no such query"}'
        return 200, "application/json", json.dumps(_query(q, full=True)).encode()
    if path.startswith("/ui"):
        return 404, "text/plain", b"not found"
    return None


def _stats(server) -> dict:
    queries = list(server._queries.values())
    states = {}
    for q in queries:
        states[q.state] = states.get(q.state, 0) + 1
    pool = {}
    try:
        from trino_tpu.runtime.buffer_pool import POOL

        pool = POOL.stats()
    except Exception:
        pass
    trace_cache = {}
    try:
        from trino_tpu.parallel.spmd import TRACE_CACHE

        trace_cache = TRACE_CACHE.stats()
    except Exception:
        pass
    workers = []
    fd = getattr(getattr(server, "runner", None), "failure_detector", None)
    if fd is not None:
        workers = fd.active_workers()
    return {
        "uptime_s": round(time.monotonic() - server.started_at, 1),
        "totalQueries": len(queries),
        "queryStates": states,
        "runningQueries": states.get("RUNNING", 0),
        "queuedQueries": states.get("QUEUED", 0),
        "finishedQueries": states.get("FINISHED", 0),
        "failedQueries": states.get("FAILED", 0),
        "activeWorkers": workers or ["local"],
        "bufferPool": pool,
        # compiled-SPMD-program cache health (retraces must stay 0 warm);
        # the full registry is the Prometheus text at /v1/metrics
        "traceCache": trace_cache,
        "metricsUri": "/v1/metrics",
    }


def _queries(server) -> list:
    return [
        _query(q)
        for q in sorted(
            server._queries.values(), key=lambda q: q.id, reverse=True
        )
    ]


def _query(q, full: bool = False) -> dict:
    doc = {
        "queryId": q.id,
        "state": q.state,
        "query": q.sql if full else q.sql[:200],
    }
    if q.error is not None:
        doc["errorName"] = q.error.get("errorName")
        if full:
            doc["error"] = q.error
    if full and q.result is not None:
        doc["columns"] = q.columns_json()
        doc["rowCount"] = len(q.result.rows)
    return doc


_PAGE = """<!DOCTYPE html>
<html><head><title>trino_tpu</title>
<style>
 body { font-family: monospace; margin: 2em; background: #111; color: #eee; }
 h1 { color: #7fd4ff; } table { border-collapse: collapse; width: 100%; }
 td, th { border: 1px solid #444; padding: 4px 8px; text-align: left; }
 th { background: #222; } .FINISHED { color: #8f8; } .FAILED { color: #f88; }
 .RUNNING { color: #ff8; } .QUEUED { color: #88f; }
 #stats span { margin-right: 2em; }
</style></head>
<body>
<h1>trino_tpu coordinator</h1>
<div id="stats">loading…</div>
<h2>queries</h2>
<table id="queries"><tr><th>id</th><th>state</th><th>sql</th></tr></table>
<script>
async function refresh() {
  const s = await (await fetch('/ui/api/stats')).json();
  document.getElementById('stats').innerHTML =
    `<span>uptime ${s.uptime_s}s</span>` +
    `<span>workers ${s.activeWorkers.length}</span>` +
    `<span>running ${s.runningQueries}</span>` +
    `<span>queued ${s.queuedQueries}</span>` +
    `<span>finished ${s.finishedQueries}</span>` +
    `<span>failed ${s.failedQueries}</span>` +
    `<span>retraces ${(s.traceCache || {}).retraces ?? '-'}</span>` +
    `<span><a href="/v1/metrics" style="color:#7fd4ff">metrics</a></span>`;
  const qs = await (await fetch('/ui/api/query')).json();
  const t = document.getElementById('queries');
  t.innerHTML = '<tr><th>id</th><th>state</th><th>sql</th></tr>' +
    qs.map(q => `<tr><td>${q.queryId}</td>` +
      `<td class="${q.state}">${q.state}</td>` +
      `<td>${q.query.replace(/</g, '&lt;')}</td></tr>`).join('');
}
refresh(); setInterval(refresh, 2000);
</script>
</body></html>
"""
