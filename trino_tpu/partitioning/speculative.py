"""Speculative join output capacity (Spark-AQE-style guess-and-retry).

The pre-PR distributed join blocked the accelerator on a host sync of the
per-probe-row match counts to size the expand program's static output
capacity.  Speculative execution replaces the sync: pick a pow2 `out_cap`
from history (or a conservative cold guess), run the fused locate+expand
program with the per-worker emitted total and an on-device overflow flag in
its outputs, and only if some worker overflowed, retry at the exact pow2
bucket of the observed totals.  The host never reads match counts before
dispatching the join; the post-hoc flag read is a tiny [W] transfer that
overlaps completed device work.

`CapacityHistory` remembers the last good capacity per join fingerprint, so
a warm query replays at the right bucket with zero retries (asserted by
`verify.device_residency` over the partitioned-join path).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from trino_tpu.ops.common import next_pow2

#: smallest speculative bucket (matches the old host-sync path's floor)
CAP_FLOOR = 1024


class CapacityHistory:
    """join fingerprint -> last good pow2 out_cap (process-wide, bounded).

    `version` bumps only when a record CHANGES the mapping (new key or new
    cap), so callers can tell "this run LEARNED a capacity" apart from the
    warm path's re-record of the same value — the signal
    tools/prewarm_manifest.py uses to treat capacity learning as part of
    the cold phase.  `snapshot`/`seed` serialize the history through the
    prewarm manifest: a restarted (or prewarming) process seeds the learned
    caps so its FIRST run takes the fused speculative path at the right
    bucket instead of re-learning — the Q3 fused_expand recompile PR 6
    flagged."""

    def __init__(self, limit: int = 1024):
        self.limit = limit
        self._caps: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        #: bumped on every mapping CHANGE (never on a same-value re-record)
        self.version = 0

    def guess(self, key, default: int) -> int:
        with self._lock:
            cap = self._caps.get(key)
            if cap is not None:
                self._caps.move_to_end(key)
                return cap
        return default

    def record(self, key, cap: int) -> None:
        with self._lock:
            if self._caps.get(key) != cap:
                self.version += 1
            self._caps[key] = cap
            self._caps.move_to_end(key)
            while len(self._caps) > self.limit:
                self._caps.popitem(last=False)

    def snapshot(self) -> list:
        """JSON-serializable [{key, cap}] (keys as reprs — they are tuples
        of strings/ints by construction, so `seed` can literal_eval them)."""
        with self._lock:
            return [
                {"key": repr(k), "cap": int(v)} for k, v in self._caps.items()
            ]

    def seed(self, entries) -> int:
        """Restore entries from a `snapshot()` (e.g. a prewarm manifest's
        cap_history section); returns how many were installed.  Entries
        whose key repr does not literal_eval (a future key shape) are
        skipped — seeding is an optimization, never a correctness
        dependency."""
        import ast

        n = 0
        for e in entries or ():
            try:
                key = ast.literal_eval(e["key"])
                cap = int(e["cap"])
            except (KeyError, TypeError, ValueError, SyntaxError):
                continue
            self.record(key, cap)
            n += 1
        return n

    def clear(self) -> None:
        with self._lock:
            self._caps.clear()
            self.version += 1


#: the process-wide history (cleared only by tests)
CAP_HISTORY = CapacityHistory()


def speculation_mode(properties):
    """Parse the `join_speculative_capacity` session property:
    -> None (off) | 0 (on, auto initial cap) | pow2 int (initial-cap
    override)."""
    try:
        raw = str(properties.get("join_speculative_capacity")).strip().lower()
    except KeyError:  # older property sets
        return 0
    if raw in ("off", "false", "no", "0"):
        return None
    if raw in ("on", "true", "yes", ""):
        return 0
    try:
        return next_pow2(max(1, int(raw)), floor=1)
    except ValueError:
        raise ValueError(
            f"join_speculative_capacity must be on|off|<initial cap>, got {raw!r}"
        )


def initial_cap(history_key, override: int):
    """Speculative capacity to launch at: the recorded history (tight —
    the exact bucket the cold sizing pass measured), else the session
    override.  Returns None when neither exists: the caller runs the cold
    sizing pass (one tiny [W] totals read) instead of speculating on a
    guess — a wrong guess either overflows (retry) or, worse, silently
    oversizes the expand and every downstream static shape."""
    cap = CAP_HISTORY.guess(history_key, 0)
    if cap:
        return cap
    return override or None


def next_cap(observed_total: int, current: int) -> int:
    """Retry bucket after an overflow at `current`."""
    return max(next_pow2(max(1, observed_total), floor=CAP_FLOOR), current * 2)
