"""Partitioning-property derivation (reference: the ActualProperties /
PropertyDerivations side of AddExchanges).

`derive_partitioning(node, resolver, n_workers)` computes, bottom-up, the
set of *placements* of a (possibly already exchange-placed) plan subtree: a
placement is an ordered tuple of symbol names S such that every row of the
subtree's output lives on worker `exchange_hash(S) % W`.  Two subtrees that
share a placement aligned through join criteria are co-partitioned — their
join needs no repartition exchange; an aggregation whose grouping keys
cover a placement has every group whole on one worker — it runs
single-stage with no exchange.

Soundness notes baked into the rules:

  * a placement on a *subset* of some consumer's keys is enough for
    co-location (equal full-key rows agree on the subset);
  * ordered tuples, not sets: the exchange hash folds key columns in
    order, so ("a", "b") and ("b", "a") are different placement functions;
  * outer joins null out one side's columns on unmatched rows, which
    breaks that side's placements (nulls co-locate only under the hash of
    their own side) — left joins keep only probe placements, full joins
    keep none.
"""

from __future__ import annotations

from trino_tpu.planner import plan as P
from trino_tpu.expr.ir import SymbolRef
from trino_tpu.partitioning.layout import scan_partitioning


def derive_partitioning(node, resolver, n_workers: int) -> tuple:
    """-> tuple of placements (each an ordered tuple of symbol names)."""
    m = _RULES.get(type(node).__name__)
    if m is None:
        # RemoteSourceNode lives in fragmenter (import cycle); match by shape
        if hasattr(node, "exchange_kind"):
            return _d_remote(node)
        return ()
    return m(node, resolver, n_workers)


def _inherit(node, resolver, n_workers):
    return derive_partitioning(node.children[0], resolver, n_workers)


def _d_scan(node, resolver, n_workers):
    hit = scan_partitioning(node, resolver, n_workers)
    if hit is None:
        return ()
    _, names, _ = hit
    return (names,)


def _d_project(node, resolver, n_workers):
    src = derive_partitioning(node.source, resolver, n_workers)
    if not src:
        return ()
    # identity refs rename placements through the projection; a placement
    # with any non-surviving column is lost
    rename = {}
    for sym, e in node.assignments:
        if isinstance(e, SymbolRef):
            rename.setdefault(e.name, sym.name)
    out = []
    for t in src:
        if all(n in rename for n in t):
            out.append(tuple(rename[n] for n in t))
    return tuple(out)


def _d_exchange(node, resolver, n_workers):
    if node.kind == "repartition" and node.partition_symbols:
        return (tuple(s.name for s in node.partition_symbols),)
    return ()


def _d_remote(node):
    if node.exchange_kind == "repartition" and node.partition_symbols:
        return (tuple(s.name for s in node.partition_symbols),)
    return ()


def join_output_placements(probe_placements, criteria, kind: str) -> tuple:
    """Placements of a join's output given the PROBE side's placements.
    Probe rows stay put, so probe placements survive for inner/left joins;
    inner joins additionally satisfy the build-side equivalents of any
    placement fully covered by the join criteria (matched rows agree on
    key values).  Full joins keep nothing (both sides gain null rows)."""
    if kind == "full":
        return ()
    out = list(probe_placements)
    if kind == "inner":
        l2r = {l.name: r.name for l, r in criteria}
        for t in probe_placements:
            if t and all(n in l2r for n in t):
                mapped = tuple(l2r[n] for n in t)
                if mapped not in out:
                    out.append(mapped)
    return tuple(out)


def _d_join(node, resolver, n_workers):
    if node.kind == "cross" or not node.criteria:
        return ()
    probe = derive_partitioning(node.left, resolver, n_workers)
    # build-side equivalents may only be claimed through criteria whose
    # hash is dictionary-independent OR whose two sides share one global
    # dictionary version — a producer-local string pair maps equal values
    # to different codes, so the mirrored claim would be unsound
    usable = hash_aligned_criteria(
        node.criteria, derive_dictionary_coding(node, resolver)
    )
    return join_output_placements(probe, usable, node.kind)


def _d_agg(node, resolver, n_workers):
    src = derive_partitioning(node.source, resolver, n_workers)
    gnames = {s.name for s in node.group_symbols}
    return tuple(t for t in src if t and set(t) <= gnames)


def _d_semi(node, resolver, n_workers):
    return derive_partitioning(node.source, resolver, n_workers)


_RULES = {
    "TableScanNode": _d_scan,
    "FilterNode": _inherit,
    "LimitNode": _inherit,
    "SortNode": _inherit,
    "TopNNode": _inherit,
    "SampleNode": _inherit,
    "UnnestNode": _inherit,
    "WindowNode": _inherit,
    "MarkDistinctNode": _inherit,
    "ProjectNode": _d_project,
    "ExchangeNode": _d_exchange,
    "JoinNode": _d_join,
    "AggregationNode": _d_agg,
    "SemiJoinNode": _d_semi,
}


def hash_aligned_criteria(criteria, coding=None) -> list:
    """Criteria pairs usable for cross-side co-location claims: both key
    types must hash dictionary-independently (plain integer kinds).  A
    dictionary-coded (string) key hashes its producer-local codes, so two
    independently-produced sides place equal strings on DIFFERENT workers —
    eliding their exchange would silently drop matches.

    The one VERSION-GATED exception (`coding`: symbol name -> (key,
    version) global dictionary ref, from `derive_dictionary_coding`): when
    both sides of a string pair carry the SAME versioned global assignment,
    equal strings provably have equal codes everywhere, so the pair hashes
    cross-side like an integer key.  Producer-local keys (no ref) and
    mixed-version pairs stay excluded."""
    from trino_tpu import types as T
    from trino_tpu.partitioning.layout import hashable_layout_type

    out = []
    for l, r in criteria:
        if hashable_layout_type(l.type) and hashable_layout_type(r.type):
            out.append((l, r))
        elif (
            coding is not None
            and T.is_string_kind(l.type)
            and T.is_string_kind(r.type)
            and coding.get(l.name) is not None
            and coding.get(l.name) == coding.get(r.name)
        ):
            out.append((l, r))
    return out


def derive_dictionary_coding(node, resolver) -> dict:
    """Bottom-up map of symbol name -> (key, version) global dictionary ref
    for every string symbol of the subtree's output that is provably coded
    under one versioned mesh-wide assignment (runtime/dictionary_service).
    Empty claims are always sound (they just keep the exclusion); a symbol
    appears ONLY when its codes survive unchanged from a registered scan:
    identity projections, filters, exchanges (global codes ship as-is),
    join pass-through, and group keys.  Derived transforms (upper(x),
    concat, ...) produce fresh dictionaries and drop out."""
    if resolver is None or not getattr(resolver, "global_dicts", True):
        return {}
    return _coding(node, resolver)


def _coding(node, resolver) -> dict:
    name = type(node).__name__
    if name == "TableScanNode":
        from trino_tpu import types as T
        from trino_tpu.runtime.dictionary_service import DICTIONARY_SERVICE

        out = {}
        for sym, col in node.assignments:
            if T.is_string_kind(sym.type):
                ref = DICTIONARY_SERVICE.coding(
                    node.handle, col, getattr(resolver, "catalogs", None)
                )
                if ref is not None:
                    out[sym.name] = ref
        return out
    if name == "ProjectNode":
        src = _coding(node.source, resolver)
        out = {}
        for sym, e in node.assignments:
            if isinstance(e, SymbolRef) and e.name in src:
                out[sym.name] = src[e.name]
        return out
    if name == "AggregationNode":
        src = _coding(node.source, resolver)
        gnames = {s.name for s in node.group_symbols}
        return {n: ref for n, ref in src.items() if n in gnames}
    # everything else (filters, exchanges, joins, sorts, ...): the union of
    # the children's claims — plan symbol names are unique, and these nodes
    # pass key columns through without re-coding.  Fragment boundaries
    # (RemoteSourceNode, no children) claim nothing.
    out = {}
    for c in node.children:
        out.update(_coding(c, resolver))
    return out


def align_through_criteria(placements, criteria, left_side: bool,
                           coding=None):
    """First placement tuple expressible entirely in `criteria` keys of the
    given side, with its opposite-side image: -> (own tuple of Symbols,
    other tuple of Symbols) or None.  Used to co-partition a join: if one
    side is already placed on (a subset of) its keys, the other side only
    needs repartitioning on the ALIGNED opposite keys to co-locate."""
    usable = hash_aligned_criteria(criteria, coding)
    if left_side:
        own = {l.name: (l, r) for l, r in usable}
    else:
        own = {r.name: (r, l) for l, r in usable}
    for t in placements:
        if t and all(n in own for n in t):
            return (
                tuple(own[n][0] for n in t),
                tuple(own[n][1] for n in t),
            )
    return None
