"""Table layouts & partitioning-aware execution.

Makes partitioning a first-class property flowing from storage to the mesh:
connectors declare hash-bucketed `TableLayout`s; `derive_partitioning`
propagates "placed on symbols S across W workers" through the plan so the
exchange placer elides repartitions for co-partitioned joins and plans
single-stage aggregations; `speculative` sizes join expands without a host
capacity sync.  See each module's docstring for the contracts.
"""

from trino_tpu.partitioning.layout import (
    GLOBAL_LAYOUTS,
    LayoutResolver,
    TableLayout,
    bucket_rows,
    declare_layout,
    drop_layout,
    hashable_layout_type,
    host_bucket_hash,
    parse_layout_property,
    scan_partitioning,
)
from trino_tpu.partitioning.properties import (
    align_through_criteria,
    derive_dictionary_coding,
    derive_partitioning,
    hash_aligned_criteria,
    join_output_placements,
)
from trino_tpu.partitioning.speculative import (
    CAP_HISTORY,
    CapacityHistory,
    initial_cap,
    next_cap,
    speculation_mode,
)

__all__ = [
    "GLOBAL_LAYOUTS",
    "LayoutResolver",
    "TableLayout",
    "bucket_rows",
    "declare_layout",
    "drop_layout",
    "hashable_layout_type",
    "host_bucket_hash",
    "parse_layout_property",
    "scan_partitioning",
    "align_through_criteria",
    "derive_dictionary_coding",
    "derive_partitioning",
    "hash_aligned_criteria",
    "join_output_placements",
    "CAP_HISTORY",
    "CapacityHistory",
    "initial_cap",
    "next_cap",
    "speculation_mode",
]
