"""Hash-bucketed table layouts (reference: connector table layouts /
SystemPartitioningHandle + Hive bucketing).

A TableLayout declares that a table's rows are (or should be) placed by
`hash(bucket_columns) % bucket_count`.  The hash is EXACTLY the exchange
data plane's row hash (`parallel/exchange._hash_rows`), mirrored here on
host numpy, so a scan that shards rows by layout puts every row on the same
worker a hash-repartition exchange on the same keys would have chosen:
co-partitioned scans make the exchange a no-op (`bucket_count` must be a
multiple of the worker count W — then `(h % B) % W == h % W`).

Layouts come from three places, consulted in order by `LayoutResolver`:

  * the `table_layouts` session property (declare layouts on generated
    TPC-H/TPC-DS tables: ``set session table_layouts =
    'tpch.sf1.lineitem:l_orderkey:8,tpch.sf1.orders:o_orderkey:8'``);
  * the process-wide registry (`declare_layout`), fed by
    ``CREATE TABLE ... WITH (bucketed_by = ARRAY['x'], bucket_count = 8)``;
  * the connector itself (`Connector.table_layout`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from trino_tpu import types as T


@dataclass(frozen=True)
class TableLayout:
    """Declared hash-bucketing of one table."""

    bucket_columns: tuple
    bucket_count: int

    def __str__(self):
        return f"bucketed_by=[{', '.join(self.bucket_columns)}] buckets={self.bucket_count}"


#: process-wide declared layouts: (catalog, schema, table) -> TableLayout
GLOBAL_LAYOUTS: dict[tuple, TableLayout] = {}


def declare_layout(qualified, bucket_columns, bucket_count: int) -> TableLayout:
    """Register a layout for `catalog.schema.table` (string or 3-tuple)."""
    if isinstance(qualified, str):
        parts = tuple(qualified.split("."))
    else:
        parts = tuple(qualified)
    if len(parts) != 3:
        raise ValueError(f"layout table must be catalog.schema.table: {qualified!r}")
    cols = tuple(str(c) for c in bucket_columns)
    n = int(bucket_count)
    if not cols or n <= 0:
        raise ValueError("a layout needs bucket columns and a positive bucket_count")
    layout = TableLayout(cols, n)
    GLOBAL_LAYOUTS[parts] = layout
    return layout


def drop_layout(qualified) -> None:
    parts = tuple(qualified.split(".")) if isinstance(qualified, str) else tuple(qualified)
    GLOBAL_LAYOUTS.pop(parts, None)


def parse_layout_property(text: str) -> dict:
    """Parse the `table_layouts` session property:
    ``cat.schema.table:col1+col2:bucket_count`` entries, comma-separated."""
    out: dict[tuple, TableLayout] = {}
    for entry in (text or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"bad table_layouts entry {entry!r} "
                "(want catalog.schema.table:col1+col2:buckets)"
            )
        name = tuple(parts[0].strip().split("."))
        if len(name) != 3:
            raise ValueError(f"bad table name in table_layouts entry {entry!r}")
        cols = tuple(c.strip() for c in parts[1].split("+") if c.strip())
        out[name] = TableLayout(cols, int(parts[2]))
    return out


class LayoutResolver:
    """handle -> Optional[TableLayout]; session property wins over the
    process registry, which wins over the connector's own declaration."""

    def __init__(self, catalogs=None, properties=None):
        self.catalogs = catalogs
        self._session: dict[tuple, TableLayout] = {}
        #: whether plan claims may lean on the global dictionary service
        #: (the `global_dictionaries` session property; default on)
        self.global_dicts = True
        if properties is not None:
            try:
                self._session = parse_layout_property(
                    properties.get("table_layouts")
                )
            except KeyError:  # older property sets
                self._session = {}
            try:
                self.global_dicts = bool(properties.get("global_dictionaries"))
            except KeyError:  # older property sets
                pass

    def __call__(self, handle) -> Optional[TableLayout]:
        key = (handle.catalog, handle.schema, handle.table)
        hit = self._session.get(key) or GLOBAL_LAYOUTS.get(key)
        if hit is not None:
            return hit
        if self.catalogs is not None:
            try:
                conn = self.catalogs.get(handle.catalog)
            except KeyError:
                return None
            return conn.table_layout(handle)
        return None


def hashable_layout_type(t) -> bool:
    """Types whose host hash provably mirrors the device exchange hash:
    plain integer-kind columns (bigint/int/date/short decimal).  Strings
    ride as producer-local dictionary codes and long decimals as limb
    planes — both excluded."""
    if T.is_string_kind(t):
        return False
    if isinstance(t, T.DecimalType) and t.is_long:
        return False
    return np.issubdtype(t.np_dtype, np.integer)


def scan_partitioning(node, resolver, n_workers: int):
    """The ONE eligibility rule for layout-aligned scans, shared by the
    planner's property derivation, the fragmenter's handle printing, and
    the runner's bucketized scan (so plan- and run-time claims can never
    diverge).  Returns (layout, partition symbol names, key channels) or
    None when `node` (a TableScanNode) has no usable layout."""
    if resolver is None:
        return None
    layout = resolver(node.handle)
    if layout is None:
        return None
    if n_workers <= 0 or layout.bucket_count % n_workers != 0:
        return None
    by_col = {c: (i, s) for i, (s, c) in enumerate(node.assignments)}
    names = []
    channels = []
    for col in layout.bucket_columns:
        hit = by_col.get(col)
        if hit is None:
            return None  # bucket column not scanned: cannot place by it
        ch, sym = hit
        if not hashable_layout_type(sym.type):
            if not _globally_coded_column(node.handle, col, sym.type, resolver):
                return None
        names.append(sym.name)
        channels.append(ch)
    return layout, tuple(names), tuple(channels)


def _globally_coded_column(handle, column, t, resolver) -> bool:
    """A string bucket column is layout-usable iff its codes are one
    versioned mesh-global assignment (runtime/dictionary_service): the
    host/device hash then runs over codes that mean the same thing on
    every worker, so `(h % B) % W == h % W` places by VALUE exactly like
    an integer key.  Producer-local dictionaries stay excluded."""
    if not T.is_string_kind(t):
        return False
    if not getattr(resolver, "global_dicts", True):
        return False
    from trino_tpu.runtime.dictionary_service import DICTIONARY_SERVICE

    return (
        DICTIONARY_SERVICE.coding(
            handle, column, getattr(resolver, "catalogs", None)
        )
        is not None
    )


def host_bucket_hash(columns, valids, cap: int) -> np.ndarray:
    """Numpy mirror of `parallel/exchange._hash_rows` over integer-kind key
    columns: identical FNV init, splitmix-style word mixing, and the NULL
    sentinel, so `host_bucket_hash(...) % W` equals the device exchange's
    destination for every row."""
    from trino_tpu.parallel.exchange import _MIX, _NULL_HASH, HASH_INIT

    h = np.full(cap, HASH_INIT, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for data, valid in zip(columns, valids):
            bits = np.asarray(data).astype(np.int64).astype(np.uint64)
            if valid is not None:
                bits = np.where(np.asarray(valid), bits, np.uint64(_NULL_HASH))
            x = (bits ^ (bits >> np.uint64(33))) * _MIX
            x = x ^ (x >> np.uint64(29))
            h = (h ^ x) * _MIX
    return h


def bucket_rows(batch, key_channels, n_workers: int) -> np.ndarray:
    """Worker destination of every live row of a HOST batch under the
    layout hash; dead rows get destination `n_workers`."""
    cols = [np.asarray(batch.columns[ch].data) for ch in key_channels]
    valids = [
        None if batch.columns[ch].valid is None else np.asarray(batch.columns[ch].valid)
        for ch in key_channels
    ]
    cap = cols[0].shape[0] if cols else len(np.asarray(batch.mask()))
    h = host_bucket_hash(cols, valids, cap)
    dest = (h % np.uint64(n_workers)).astype(np.int64)
    return np.where(np.asarray(batch.mask()), dest, n_workers)
