"""Batch builders — the test/ingest-side mirror of RowPagesBuilder.

Reference role: core/trino-main/src/test/java/io/trino/RowPagesBuilder.java and
the connector-side PageBuilder (spi/PageBuilder.java): turn row-oriented host
data (python rows, numpy arrays, pandas frames) into device Batches.
"""

from __future__ import annotations

import datetime
from decimal import Context, Decimal
from typing import Optional, Sequence

import numpy as np

from trino_tpu.types import (
    Type,
    DecimalType,
    DATE,
    TIMESTAMP,
    TIMESTAMP_TZ,
    is_string_kind,
    pack_tz,
)
from trino_tpu.columnar.column import Column
from trino_tpu.columnar.batch import Batch
from trino_tpu.columnar.dictionary import StringDictionary

_EPOCH_DATE = datetime.date(1970, 1, 1)
_EPOCH_TS = datetime.datetime(1970, 1, 1)


def _to_device_scalar(v, t: Type):
    if isinstance(t, DecimalType):
        if isinstance(v, Decimal):
            return int(v.scaleb(t.scale).to_integral_value())
        return int(round(float(v) * t.scale_factor))
    if t is DATE and isinstance(v, datetime.date):
        return (v - _EPOCH_DATE).days
    if t is TIMESTAMP and isinstance(v, datetime.datetime):
        return int((v - _EPOCH_TS).total_seconds() * 1_000_000)
    if t.name == "time" and isinstance(v, datetime.time):
        return (
            (v.hour * 3600 + v.minute * 60 + v.second) * 1_000_000
            + v.microsecond
        )
    if t is TIMESTAMP_TZ and isinstance(v, datetime.datetime):
        off = v.utcoffset()
        off_min = int(off.total_seconds() // 60) if off is not None else 0
        utc = v.replace(tzinfo=None) - datetime.timedelta(minutes=off_min)
        # timedelta floor-division: float total_seconds()*1000 truncates
        # toward zero, putting every pre-epoch fractional value 1 ms high
        millis = (utc - _EPOCH_TS) // datetime.timedelta(milliseconds=1)
        return pack_tz(millis, off_min)
    return v


def column_from_values(values: Sequence, t: Type) -> Column:
    n = len(values)
    valid_list = [v is not None for v in values]
    has_nulls = not all(valid_list)
    valid = np.array(valid_list, dtype=bool) if has_nulls else None
    if is_string_kind(t) or (t.is_dictionary_encoded):
        present = sorted({v for v in values if v is not None})
        d = StringDictionary(present)
        codes = d.encode([v if v is not None else None for v in values])
        return Column(codes, t, valid, d)
    if isinstance(t, DecimalType) and t.is_long:
        # long decimal: [n, 2] int64 limb planes (types/int128.py)
        from trino_tpu.types.int128 import split_py

        arr2 = np.zeros((n, 2), dtype=np.int64)
        # explicit wide context: the default 28-digit context would round
        # 29+ digit values during scaleb
        ctx = Context(prec=60)
        for i, v in enumerate(values):
            if v is None:
                continue
            if isinstance(v, Decimal):
                scaled = int(
                    v.scaleb(t.scale, context=ctx).to_integral_value(
                        context=ctx
                    )
                )
            elif isinstance(v, int):
                scaled = v * t.scale_factor  # exact python-int path
            else:
                scaled = int(round(float(v) * t.scale_factor))
            arr2[i, 0], arr2[i, 1] = split_py(scaled)
        return Column(arr2, t, valid)
    # fast path: plain python numbers convert in one C-level call (also what
    # makes the scaled-writer thread pool worthwhile — the conversion runs
    # outside the GIL's per-object churn)
    # (decimals always go per-value: even plain int/float inputs must scale)
    if not has_nulls and not isinstance(t, DecimalType):
        try:
            return Column(np.asarray(values, dtype=t.np_dtype), t, None)
        except (TypeError, ValueError):
            pass  # date/timestamp objects: per-value conversion below
    arr = np.zeros(n, dtype=t.np_dtype)
    for i, v in enumerate(values):
        if v is not None:
            arr[i] = _to_device_scalar(v, t)
    return Column(arr, t, valid)


def batch_from_rows(types: Sequence[Type], rows: Sequence[Sequence]) -> Batch:
    if not types:
        # zero-column batch (e.g. SELECT without FROM): row count rides the mask
        return Batch([], np.ones(len(rows), dtype=bool))
    cols = []
    for ch, t in enumerate(types):
        cols.append(column_from_values([r[ch] for r in rows], t))
    return Batch(cols)


def batch_from_arrays(
    arrays: Sequence[np.ndarray],
    types: Sequence[Type],
    valids: Optional[Sequence[Optional[np.ndarray]]] = None,
    dictionaries: Optional[Sequence[Optional[StringDictionary]]] = None,
) -> Batch:
    cols = []
    for i, (a, t) in enumerate(zip(arrays, types)):
        valid = valids[i] if valids else None
        d = dictionaries[i] if dictionaries else None
        cols.append(Column.from_numpy(a, t, valid, d))
    return Batch(cols)


class RowBatchBuilder:
    """Append rows, then build a (optionally padded) Batch."""

    def __init__(self, types: Sequence[Type]):
        self.types = list(types)
        self.rows: list[list] = []

    def row(self, *values) -> "RowBatchBuilder":
        assert len(values) == len(self.types)
        self.rows.append(list(values))
        return self

    def build(self, capacity: Optional[int] = None) -> Batch:
        b = batch_from_rows(self.types, self.rows)
        if capacity is None or capacity == len(self.rows):
            return b
        return pad_batch(b, capacity)


def pad_batch(b: Batch, capacity: int) -> Batch:
    """Pad to a larger static capacity; padded rows are dead."""
    n = b.capacity
    assert capacity >= n, (capacity, n)
    if capacity == n:
        return b
    pad = capacity - n
    cols = []
    for c in b.columns:
        data = np.concatenate(
            [np.asarray(c.data), np.zeros(pad, dtype=c.type.np_dtype)]
        )
        valid = None
        if c.valid is not None:
            valid = np.concatenate([np.asarray(c.valid), np.zeros(pad, dtype=bool)])
        cols.append(Column(data, c.type, valid, c.dictionary))
    mask = np.concatenate(
        [
            np.asarray(b.mask()),
            np.zeros(pad, dtype=bool),
        ]
    )
    return Batch(cols, mask)
