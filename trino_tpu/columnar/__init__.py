"""Device-resident columnar data model — the Page/Block analog.

Reference roles:
  - spi/Page.java:31        -> Batch (a bundle of equal-length columns)
  - spi/block/Block.java    -> Column (values + validity mask)
  - DictionaryBlock         -> order-preserving StringDictionary + i32 codes
  - RowPagesBuilder (tests) -> builders.RowBatchBuilder

Design: batches are fixed-capacity struct-of-arrays with boolean row masks so
that every downstream computation is shape-stable under jit.  Selection never
reallocates on device; it ANDs masks.  Compaction happens only at exchange /
result boundaries.
"""

from trino_tpu.columnar.dictionary import StringDictionary
from trino_tpu.columnar.column import Column
from trino_tpu.columnar.batch import Batch
from trino_tpu.columnar.builders import (
    RowBatchBuilder,
    batch_from_arrays,
    batch_from_rows,
)

__all__ = [
    "StringDictionary",
    "Column",
    "Batch",
    "RowBatchBuilder",
    "batch_from_arrays",
    "batch_from_rows",
]
