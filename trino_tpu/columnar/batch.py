"""Batch: the engine's Page (reference: spi/Page.java:31).

A Batch is a tuple of equal-capacity Columns plus an optional boolean row mask.
Filtering ANDs the mask (never reallocates on device); operators that need
dense input (exchange partitioning, result rendering) compact explicitly.
Positional channels, not names — the planner tracks symbols->channels exactly
like the reference's LocalExecutionPlanner layout mapping.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.columnar.column import Column


class Batch:
    __slots__ = ("columns", "row_mask")

    def __init__(self, columns: Sequence[Column], row_mask=None):
        self.columns = tuple(columns)
        self.row_mask = row_mask  # None => all rows live

    # -- shape ---------------------------------------------------------------

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        if self.row_mask is not None:
            return self.row_mask.shape[0]
        return 0

    @property
    def width(self) -> int:
        return len(self.columns)

    def mask(self):
        """Materialized live-row mask, shape [capacity]."""
        if self.row_mask is None:
            return jnp.ones(self.capacity, dtype=bool)
        return self.row_mask

    def count(self):
        """Device scalar: number of live rows."""
        if self.row_mask is None:
            return jnp.asarray(self.capacity, dtype=jnp.int64)
        return jnp.sum(self.row_mask, dtype=jnp.int64)

    # -- transforms ----------------------------------------------------------

    def column(self, i: int) -> Column:
        return self.columns[i]

    def with_columns(self, columns: Sequence[Column]) -> "Batch":
        return Batch(columns, self.row_mask)

    def append_column(self, col: Column) -> "Batch":
        return Batch(self.columns + (col,), self.row_mask)

    def project(self, channels: Sequence[int]) -> "Batch":
        return Batch([self.columns[i] for i in channels], self.row_mask)

    def filter(self, keep_mask) -> "Batch":
        """AND a boolean mask into the live-row mask."""
        if self.row_mask is None:
            return Batch(self.columns, keep_mask)
        return Batch(self.columns, jnp.logical_and(self.row_mask, keep_mask))

    def gather(self, indices, valid=None) -> "Batch":
        """Row gather; `valid` marks which gathered slots are live."""
        cols = [c.gather(indices) for c in self.columns]
        if valid is None and self.row_mask is not None:
            valid = jnp.take(self.row_mask, indices, axis=0, mode="clip")
        return Batch(cols, valid)

    def compact_device(self, out_capacity: Optional[int] = None) -> "Batch":
        """Pack live rows to the front (stable) via cumsum-scatter.

        Shape-stable: output capacity is static (`out_capacity` or input
        capacity); trailing slots are dead.  This is the selection-vector ->
        dense step the reference does in PageProcessor output.
        """
        cap = self.capacity
        outc = out_capacity or cap
        m = self.mask()
        pos = jnp.cumsum(m) - 1  # target slot per live row
        idx = jnp.where(m, pos, outc)  # dead rows scatter out of range
        n = jnp.sum(m)
        # inverse permutation: for each output slot, which input row
        inv = jnp.zeros(outc + 1, dtype=jnp.int64).at[idx].set(
            jnp.arange(cap, dtype=jnp.int64), mode="drop"
        )[:outc]
        live = jnp.arange(outc, dtype=jnp.int64) < n
        cols = [c.gather(inv) for c in self.columns]
        return Batch(cols, live)

    # -- host-side -----------------------------------------------------------

    def device_put(self, device=None) -> "Batch":
        return jax.device_put(self, device)

    def block_until_ready(self) -> "Batch":
        for c in self.columns:
            if hasattr(c.data, "block_until_ready"):
                c.data.block_until_ready()
        return self

    def num_rows_host(self) -> int:
        if self.row_mask is None:
            return self.capacity
        return int(np.asarray(jnp.sum(self.row_mask)))

    def to_pylist(self) -> list[list]:
        """Rows of python values (live rows only, in order).

        All column transfers are STARTED before any is awaited
        (copy_to_host_async): device_get alone awaits leaves one at a time,
        paying a full round trip per column when the device sits behind a
        remote tunnel."""
        host = device_get_async(self)
        rm = None if host.row_mask is None else np.asarray(host.row_mask)
        cols = [c.to_pylist(rm) for c in host.columns]
        return [list(r) for r in zip(*cols)] if cols else []

    def __repr__(self) -> str:  # pragma: no cover
        return f"Batch(cap={self.capacity}, width={self.width})"


def device_get_async(tree):
    """device_get with all leaf transfers launched up front — one round-trip
    latency for the whole pytree instead of one per leaf."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "copy_to_host_async"):
            try:
                leaf.copy_to_host_async()
            except Exception:
                pass  # backend without async copies: plain get below
    return jax.device_get(tree)


def _batch_flatten(b: Batch):
    return (b.columns, b.row_mask), None


def _batch_unflatten(aux, children):
    columns, row_mask = children
    return Batch(columns, row_mask)


jax.tree_util.register_pytree_node(Batch, _batch_flatten, _batch_unflatten)


def concat_batches(batches: Sequence[Batch]) -> Batch:
    """Host-side concat (used by accumulating operators between jit steps).

    Dictionary-encoded columns whose batches carry different dictionaries are
    recoded into a union dictionary (reference analog: DictionaryBlock
    compaction when appending across pages)."""
    assert batches
    width = batches[0].width
    cols = []
    for ch in range(width):
        parts = [b.columns[ch] for b in batches]
        dictionary = None
        dicts = [p.dictionary for p in parts]
        if any(d is not None for d in dicts):
            from trino_tpu.columnar.dictionary import union_many

            dictionary, tables = union_many(dicts)
            parts = [
                p
                if table is None
                else Column(
                    jnp.take(
                        jnp.asarray(table), jnp.asarray(p.data, jnp.int32), mode="clip"
                    ),
                    p.type,
                    p.valid,
                    dictionary,
                )
                for p, table in zip(parts, tables)
            ]
        lengths = None
        if any(p.lengths is not None for p in parts):
            # array columns: right-pad every part to the widest K.  Parts
            # with lengths=None carry 1-D data (no elements) and are lifted
            # to an all-empty [capacity, k] layout first.  Map columns pack
            # keys+values halves, so each half pads separately.
            from trino_tpu.types import MapType

            is_map = isinstance(parts[0].type, MapType)
            k = max(
                (p.data.shape[1] for p in parts if p.lengths is not None),
                default=1,
            )
            k = max(k, 2 if is_map else 1)

            def _lift(p):
                if p.lengths is None:
                    return Column(
                        jnp.zeros((p.capacity, k), dtype=p.data.dtype),
                        p.type,
                        p.valid,
                        p.dictionary,
                        jnp.zeros(p.capacity, jnp.int32),
                    )
                if p.data.shape[1] == k:
                    return p
                if is_map:
                    half = p.data.shape[1] // 2
                    pad = (k - p.data.shape[1]) // 2
                    data = jnp.concatenate(
                        [
                            jnp.pad(p.data[:, :half], ((0, 0), (0, pad))),
                            jnp.pad(p.data[:, half:], ((0, 0), (0, pad))),
                        ],
                        axis=1,
                    )
                else:
                    data = jnp.pad(
                        p.data, ((0, 0), (0, k - p.data.shape[1]))
                    )
                return Column(
                    data, p.type, p.valid, p.dictionary, p.lengths
                )

            parts = [_lift(p) for p in parts]
            lengths = jnp.concatenate(
                [
                    (
                        p.lengths
                        if p.lengths is not None
                        else jnp.zeros(p.capacity, jnp.int32)
                    )
                    for p in parts
                ]
            )
        data = jnp.concatenate([p.data for p in parts])
        if any(p.valid is not None for p in parts):
            valid = jnp.concatenate([p.valid_mask() for p in parts])
        else:
            valid = None
        c0 = parts[0]
        cols.append(Column(data, c0.type, valid, dictionary, lengths))
    if any(b.row_mask is not None for b in batches):
        mask = jnp.concatenate([b.mask() for b in batches])
    else:
        mask = None
    return Batch(cols, mask)
