"""Order-preserving string dictionaries.

The engine's answer to variable-width data on a fixed-width device (reference:
spi/block/DictionaryBlock.java + VariableWidthBlock.java): strings are encoded
once, host-side, into i32 codes whose numeric order equals the lexicographic
order of the values.  Device kernels then compare/sort/join on codes; only
ingest and final result rendering touch bytes.

String *functions* (LIKE, substr, ||, upper, ...) evaluate host-side over the
dictionary (cardinality, not row count) and become device gathers through a
code-indexed lookup table — an O(|dict|) precompute instead of an O(rows)
scalar loop, which is exactly the trade a TPU wants.
"""

from __future__ import annotations

import bisect

import numpy as np


class StringDictionary:
    """Immutable sorted dictionary of strings; code == rank.

    ``values`` are unique and sorted, so ``code_a < code_b`` iff
    ``value_a < value_b``.  Null is NOT in the dictionary — nulls live in the
    column validity mask with a device fill value of 0.
    """

    #: _nbytes: lazily cached device-adjacent footprint
    #: (runtime/memory.dictionary_bytes) — cached on the object because an
    #: id()-keyed side table would survive address recycling
    __slots__ = ("values", "_index", "_hash", "_nbytes")

    def __init__(self, values):
        vals = tuple(values)
        assert all(
            vals[i] < vals[i + 1] for i in range(len(vals) - 1)
        ), "dictionary values must be unique and sorted"
        object.__setattr__(self, "values", vals)
        object.__setattr__(self, "_index", None)
        object.__setattr__(self, "_hash", None)
        object.__setattr__(self, "_nbytes", None)

    def __setattr__(self, name, value):  # immutability
        raise AttributeError("StringDictionary is immutable")

    @classmethod
    def from_unsorted(cls, values) -> "StringDictionary":
        return cls(sorted(set(values)))

    def __len__(self) -> int:
        return len(self.values)

    def __hash__(self) -> int:
        h = self._hash
        if h is None:
            h = hash(self.values)
            object.__setattr__(self, "_hash", h)
        return h

    def __eq__(self, other) -> bool:
        return isinstance(other, StringDictionary) and (
            self is other or self.values == other.values
        )

    @property
    def index(self) -> dict:
        ix = self._index
        if ix is None:
            ix = {v: i for i, v in enumerate(self.values)}
            object.__setattr__(self, "_index", ix)
        return ix

    def code_of(self, value: str) -> int:
        """Exact-match code, -1 if absent."""
        return self.index.get(value, -1)

    def encode(self, values, out=None) -> np.ndarray:
        """Encode an iterable of strings (None -> 0, caller tracks nulls)."""
        ix = self.index
        arr = np.fromiter(
            (0 if v is None else ix[v] for v in values),
            dtype=np.int32,
            count=len(values),
        )
        return arr

    def decode(self, codes: np.ndarray) -> list:
        vals = self.values
        return [vals[int(c)] for c in codes]

    # -- range positioning for order-preserving predicates ------------------

    def lower_bound(self, value: str) -> int:
        """Smallest code whose value >= `value` (len(dict) if none)."""
        return bisect.bisect_left(self.values, value)

    def upper_bound(self, value: str) -> int:
        """Smallest code whose value > `value`."""
        return bisect.bisect_right(self.values, value)

    def prefix_range(self, prefix: str) -> tuple[int, int]:
        """[lo, hi) code range of values starting with `prefix`."""
        if not prefix:
            return 0, len(self.values)
        lo = bisect.bisect_left(self.values, prefix)
        last = prefix[-1]
        if ord(last) >= 0x10FFFF:
            # cannot form a successor string; scan is fine at dict cardinality
            hi = lo
            while hi < len(self.values) and self.values[hi].startswith(prefix):
                hi += 1
            return lo, hi
        hi = bisect.bisect_left(self.values, prefix[:-1] + chr(ord(last) + 1))
        return lo, hi

    def predicate_table(self, fn) -> np.ndarray:
        """Evaluate a python predicate over every dictionary value.

        Returns a bool[|dict|] lookup table; callers gather it by code on
        device.  This is how LIKE / regexp / prefix predicates run (reference
        role: likematcher/LikeMatcher.java, but amortized over the dictionary).
        """
        return np.fromiter(
            (bool(fn(v)) for v in self.values), dtype=bool, count=len(self.values)
        )

    def map_table(self, fn, out_dictionary: "StringDictionary") -> np.ndarray:
        """i32[|dict|] table mapping each value through a string->string fn
        into codes of `out_dictionary` (for substr/upper/trim/|| projections)."""
        ix = out_dictionary.index
        return np.fromiter(
            (ix[fn(v)] for v in self.values), dtype=np.int32, count=len(self.values)
        )

    @property
    def max_len(self) -> int:
        return max((len(v) for v in self.values), default=0)


class _LazySeq:
    """Read-only sequence computing values on demand (bisect-compatible)."""

    __slots__ = ("fn", "n")

    def __init__(self, fn, n: int):
        self.fn = fn
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return tuple(self.fn(j) for j in range(*i.indices(self.n)))
        if i < 0:
            i += self.n
        if not 0 <= i < self.n:
            raise IndexError(i)
        return self.fn(i)

    def __iter__(self):
        return (self.fn(i) for i in range(self.n))

    def __add__(self, other):
        return tuple(self) + tuple(other)

    def __radd__(self, other):
        return tuple(other) + tuple(self)


class PatternDictionary(StringDictionary):
    """Dictionary whose value at code i is computed by a *monotone* function
    (e.g. 'Customer#%09d' % (i+1)) — zero-padded formats sort lexicographically
    in numeric order, so the order-preserving invariant holds without ever
    materializing the values.  Used for the huge formatted-name columns
    (c_name, s_name, o_clerk at SF100 would otherwise cost GBs host-side).
    """

    __slots__ = ("pattern_key",)

    def __init__(self, fn, n: int, pattern_key):
        object.__setattr__(self, "values", _LazySeq(fn, n))
        object.__setattr__(self, "_index", None)
        object.__setattr__(self, "_hash", None)
        object.__setattr__(self, "pattern_key", pattern_key)

    def __hash__(self):
        return hash(("pattern", self.pattern_key, len(self.values)))

    def __eq__(self, other):
        if isinstance(other, PatternDictionary):
            return (
                self.pattern_key == other.pattern_key
                and len(self.values) == len(other.values)
            )
        return isinstance(other, StringDictionary) and tuple(self.values) == tuple(
            getattr(other, "values", ())
        )

    @property
    def index(self) -> dict:
        raise TypeError(
            "PatternDictionary has no materialized index; use code_of/bounds"
        )

    def code_of(self, value: str) -> int:
        lo = bisect.bisect_left(self.values, value)
        if lo < len(self.values) and self.values[lo] == value:
            return lo
        return -1

    def encode(self, values, out=None):
        return np.fromiter(
            (0 if v is None else self.code_of(v) for v in values),
            dtype=np.int32,
            count=len(values),
        )


class UnorderedDictionary(StringDictionary):
    """Unique but NOT sorted values — the shape of an append-only global
    dictionary epoch (runtime/dictionary_service.extend): codes of the
    original prefix keep their meaning, appended values take the next free
    codes.  Equality semantics (joins, group-bys, =/IN predicates, late
    materialization) are order-independent and work unchanged; the
    order-DEPENDENT operations (range predicates, LIKE prefix ranges,
    code-order sorting) raise instead of silently misordering — a consumer
    needing order must re-sort values into a fresh ordered dictionary.
    """

    __slots__ = ()

    def __init__(self, values):
        vals = tuple(values)
        assert len(set(vals)) == len(vals), "dictionary values must be unique"
        object.__setattr__(self, "values", vals)
        object.__setattr__(self, "_index", None)
        object.__setattr__(self, "_hash", None)
        object.__setattr__(self, "_nbytes", None)

    def _no_order(self, op: str):
        raise TypeError(
            f"{op} needs an order-preserving dictionary; this is an "
            "append-only epoch (codes are not rank-ordered)"
        )

    def lower_bound(self, value: str) -> int:
        self._no_order("lower_bound")

    def upper_bound(self, value: str) -> int:
        self._no_order("upper_bound")

    def prefix_range(self, prefix: str):
        self._no_order("prefix_range")


def union_many(dicts):
    """Merge N dictionaries; returns (merged, [recode tables]) where table[i]
    maps dict i's codes -> merged codes (None when already identical).

    A None entry means a dictionary-less varchar column, which under this
    engine's encoding invariant is ALL-NULL (e.g. a NULL literal branch of a
    grouping-sets union): it contributes no values and needs no recode —
    its code payload is masked by the validity bitmap."""
    present = [d for d in dicts if d is not None]
    if not present:
        return None, [None] * len(dicts)
    first = present[0]
    if all(d is first or d == first for d in present):
        return first, [None] * len(dicts)
    merged = StringDictionary.from_unsorted(
        [v for d in present for v in d.values]
    )
    ix = merged.index
    tables = []
    for d in dicts:
        if d is None or d is merged:
            tables.append(None)
        else:
            tables.append(
                np.fromiter((ix[v] for v in d.values), dtype=np.int32, count=len(d))
            )
    return merged, tables


def union_dictionaries(a: StringDictionary, b: StringDictionary):
    """Merge two dictionaries; returns (merged, recode_a, recode_b) where
    recode_x is an i32 table mapping old codes -> merged codes."""
    merged, (ra, rb) = union_many([a, b])
    ident = np.arange(len(merged), dtype=np.int32)
    return merged, (ra if ra is not None else ident[: len(a)]), (
        rb if rb is not None else ident[: len(b)]
    )
