"""Column: a typed device array plus optional validity mask.

Reference role: spi/block/Block.java (and its 70 concrete blocks).  Where the
reference has per-encoding block classes (RunLength, Dictionary, VariableWidth,
...), the device representation is always dense fixed-width values; dictionary
encoding lives in the Column's `dictionary` metadata, and RLE is simply a
broadcasted array (XLA folds it).

Column is a registered pytree so it can flow through jit boundaries: the
arrays are leaves, the (type, dictionary) pair is static aux data — changing a
dictionary identity therefore retraces, which is what we want since host-side
predicate tables are baked per dictionary.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.types import Type
from trino_tpu.columnar.dictionary import StringDictionary


class Column:
    __slots__ = ("data", "valid", "type", "dictionary", "lengths")

    def __init__(
        self,
        data,
        type: Type,
        valid=None,
        dictionary: Optional[StringDictionary] = None,
        lengths=None,
    ):
        self.data = data
        self.type = type
        self.valid = valid  # None => no nulls
        self.dictionary = dictionary
        # array(T) columns: data is [capacity, K] (K = padded element slots),
        # `lengths` is the per-row element count (int32 [capacity]).  The
        # reference's ArrayBlock offsets (spi/block/ArrayBlock.java) become a
        # rectangular padded layout so XLA keeps static shapes.
        self.lengths = lengths

    # -- shape ---------------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def may_have_nulls(self) -> bool:
        return self.valid is not None

    # -- construction --------------------------------------------------------

    @classmethod
    def from_numpy(
        cls,
        values: np.ndarray,
        type: Type,
        valid: Optional[np.ndarray] = None,
        dictionary: Optional[StringDictionary] = None,
    ) -> "Column":
        data = np.asarray(values, dtype=type.np_dtype)
        v = None if valid is None else np.asarray(valid, dtype=bool)
        return cls(data, type, v, dictionary)

    @classmethod
    def from_strings(cls, values, type: Type) -> "Column":
        """Encode python strings (None allowed) into a fresh dictionary."""
        present = [v for v in values if v is not None]
        d = StringDictionary.from_unsorted(present)
        codes = d.encode(values)
        valid = None
        if len(present) != len(values):
            valid = np.fromiter(
                (v is not None for v in values), dtype=bool, count=len(values)
            )
        return cls(codes, type, valid, d)

    # -- transforms (device-safe, shape preserving) --------------------------

    def with_valid(self, valid) -> "Column":
        return Column(self.data, self.type, valid, self.dictionary, self.lengths)

    def gather(self, indices) -> "Column":
        data = jnp.take(self.data, indices, axis=0, mode="clip")
        valid = (
            None
            if self.valid is None
            else jnp.take(self.valid, indices, axis=0, mode="clip")
        )
        lengths = (
            None
            if self.lengths is None
            else jnp.take(self.lengths, indices, axis=0, mode="clip")
        )
        return Column(data, self.type, valid, self.dictionary, lengths)

    def valid_mask(self):
        """Always-materialized bool mask (shape [capacity])."""
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.valid

    # -- host-side materialization ------------------------------------------

    def to_numpy(self) -> tuple[np.ndarray, Optional[np.ndarray]]:
        data = np.asarray(self.data)
        valid = None if self.valid is None else np.asarray(self.valid)
        return data, valid

    def to_pylist(self, row_mask: Optional[np.ndarray] = None) -> list:
        """Decode to python objects (strings/decimals rendered)."""
        from trino_tpu.types import DecimalType, DATE, TIMESTAMP

        data, valid = self.to_numpy()
        n = data.shape[0]
        if row_mask is None:
            rows = range(n)
        else:
            rows = np.nonzero(np.asarray(row_mask))[0]
        out = []
        t = self.type
        if self.lengths is not None:
            from trino_tpu.types import ArrayType, MapType, is_string_kind

            lens = np.asarray(self.lengths)
            if isinstance(t, MapType):
                k = data.shape[1] // 2
                kd = self.dictionary if is_string_kind(t.key) else None
                vd = self.dictionary if is_string_kind(t.value) else None
                for i in rows:
                    if valid is not None and not valid[i]:
                        out.append(None)
                        continue
                    n = int(lens[i])
                    keys = Column(data[i, :k][:n], t.key, None, kd).to_pylist()
                    vals = Column(data[i, k:][:n], t.value, None, vd).to_pylist()
                    out.append(dict(zip(keys, vals)))
                return out
            elem = t.element if isinstance(t, ArrayType) else t
            for i in rows:
                if valid is not None and not valid[i]:
                    out.append(None)
                else:
                    row = Column(
                        data[i, : int(lens[i])], elem, None, self.dictionary
                    )
                    out.append(row.to_pylist())
            return out
        is_dec = isinstance(t, DecimalType)
        is_long_dec = is_dec and t.is_long and data.ndim == 2
        if is_long_dec:
            from decimal import Context, Decimal

            from trino_tpu.types.int128 import join_py

            # hoisted: the default 28-digit context rounds 29+ digit values,
            # and constructing the wide one per row is pure overhead
            _ldec_ctx = Context(prec=60)
        for i in rows:
            if valid is not None and not valid[i]:
                out.append(None)
            elif self.dictionary is not None:
                out.append(self.dictionary.values[int(data[i])])
            elif is_long_dec:
                out.append(
                    Decimal(join_py(int(data[i, 0]), int(data[i, 1]))).scaleb(
                        -t.scale, context=_ldec_ctx
                    )
                )
            elif is_dec:
                from decimal import Decimal

                out.append(Decimal(int(data[i])).scaleb(-t.scale))
            elif t is DATE:
                import datetime

                out.append(
                    datetime.date(1970, 1, 1) + datetime.timedelta(days=int(data[i]))
                )
            elif t is TIMESTAMP:
                import datetime

                out.append(
                    datetime.datetime(1970, 1, 1)
                    + datetime.timedelta(microseconds=int(data[i]))
                )
            elif t.name == "time":
                import datetime

                us = int(data[i]) % 86_400_000_000
                out.append(
                    (
                        datetime.datetime(1970, 1, 1)
                        + datetime.timedelta(microseconds=us)
                    ).time()
                )
            elif t.name == "interval year to month":
                mo = int(data[i])
                sign = "-" if mo < 0 else ""
                out.append(f"{sign}{abs(mo) // 12}-{abs(mo) % 12}")
            elif t.name == "interval day to second":
                us = int(data[i])
                sign = "-" if us < 0 else ""
                us = abs(us)
                d_, rem = divmod(us, 86_400_000_000)
                h_, rem = divmod(rem, 3_600_000_000)
                m_, rem = divmod(rem, 60_000_000)
                s_, frac = divmod(rem, 1_000_000)
                out.append(
                    f"{sign}{d_} {h_:02d}:{m_:02d}:{s_:02d}.{frac // 1000:03d}"
                )
            elif t.name == "timestamp with time zone":
                import datetime

                from trino_tpu.types import unpack_tz_millis, unpack_tz_offset

                p = int(data[i])
                off = int(unpack_tz_offset(p))
                tz = datetime.timezone(datetime.timedelta(minutes=off))
                out.append(
                    datetime.datetime.fromtimestamp(
                        unpack_tz_millis(p) / 1000.0, tz
                    )
                )
            elif np.issubdtype(data.dtype, np.floating):
                out.append(float(data[i]))
            elif data.dtype == np.dtype(bool):
                out.append(bool(data[i]))
            else:
                out.append(int(data[i]))
        return out

    def __repr__(self) -> str:  # pragma: no cover
        return f"Column({self.type.name}, cap={self.data.shape[0]}, nulls={self.valid is not None})"


def _column_flatten(c: Column):
    return (c.data, c.valid, c.lengths), (c.type, c.dictionary)


def _column_unflatten(aux, children):
    type_, dictionary = aux
    data, valid, lengths = children
    return Column(data, type_, valid, dictionary, lengths)


jax.tree_util.register_pytree_node(Column, _column_flatten, _column_unflatten)
