"""Filesystem SPI: one seam between storage consumers and where bytes live.

Reference: lib/trino-filesystem/.../TrinoFileSystem.java (+ the S3/GCS/Azure
implementations and plugin/trino-exchange-filesystem's
S3FileSystemExchangeStorage) — every reference component that persists state
(FTE spool, iceberg metadata/data, hive splits) goes through ONE interface so
remote object stores are a configuration choice, not a code change.

This engine's consumers (runtime/fte.py spool, connectors/iceberg.py, the
persistent XLA compile cache and prewarm manifests in runtime/prewarm.py)
resolve their filesystem through `filesystem_for(location)`:

  * plain paths / `file://` -> LocalFileSystem (the only implementation this
    image can exercise — it has no object-store endpoint and zero egress)
  * `s3://`, `gs://`, `abfs://` -> raises with the scheme name, so pointing
    the spool at an object store fails loudly at configuration time instead
    of scattering NotImplementedErrors at first IO

The interface is intentionally byte-oriented (read/write/list/delete/exists)
— the npz/parquet codecs stay in the consumers, matching the reference split
between TrinoFileSystem (bytes) and the format readers above it.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional


class FileSystem:
    """Byte-level storage operations under a root location."""

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def append(self, path: str, data: bytes) -> None:
        """Append bytes to a (possibly absent) file — the audit-log /
        JSONL-sink primitive.  NOT atomic across writers; callers needing
        single-writer semantics serialize themselves (an object-store
        implementation would express this as multipart upload parts)."""
        raise NotImplementedError

    def size(self, path: str) -> int:
        """Current byte size (0 when absent) — size-based log rotation."""
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Move a file, replacing any existing destination (log-segment
        rotation).  Default: copy-then-delete through the byte interface
        — correct anywhere, O(size); implementations with a native move
        (local os.replace, object-store server-side copy) override it."""
        self.write(dst, self.read(src))
        self.delete(src)

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> Iterable[str]:
        raise NotImplementedError

    def mtime(self, path: str) -> float:
        """Last-modified time, epoch seconds (spool GC ages files by it).
        Raises OSError when the path vanished."""
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def delete_recursive(self, path: str) -> None:
        """Remove a directory tree (spool/spill cleanup).  Lives on the SPI
        so cleanup follows the files to whatever storage hosts them — an
        object-store implementation expresses this as a prefix delete, not
        a local rmtree."""
        raise NotImplementedError

    def open_input(self, path: str):
        """File-like handle for libraries that stream (pyarrow, numpy)."""
        raise NotImplementedError

    def open_output(self, path: str):
        """Writable file-like handle (streaming writes; the local
        implementation writes in place — callers needing atomic publish
        use write())."""
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    """Reference analog: filesystem/local/LocalFileSystem.java."""

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic publish (spool/iceberg commits)

    def append(self, path: str, data: bytes) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "ab") as f:
            f.write(data)

    def size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError:
            return 0

    def rename(self, src: str, dst: str) -> None:
        d = os.path.dirname(dst)
        if d:
            os.makedirs(d, exist_ok=True)
        os.replace(src, dst)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def delete(self, path: str) -> None:
        if os.path.isfile(path):
            os.remove(path)

    def list(self, prefix: str) -> Iterable[str]:
        if not os.path.isdir(prefix):
            return []
        return sorted(
            os.path.join(prefix, n) for n in os.listdir(prefix)
        )

    def mtime(self, path: str) -> float:
        return os.path.getmtime(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete_recursive(self, path: str) -> None:
        import shutil

        shutil.rmtree(path, ignore_errors=True)

    def open_input(self, path: str):
        return open(path, "rb")

    def open_output(self, path: str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")


_REMOTE_SCHEMES = ("s3://", "gs://", "abfs://", "abfss://", "hdfs://")


def filesystem_for(location: Optional[str]) -> FileSystem:
    """Resolve the FileSystem for a location (the TrinoFileSystemFactory
    role).  Local paths and file:// resolve to LocalFileSystem; remote
    object-store schemes fail loudly until an implementation lands."""
    loc = location or ""
    for scheme in _REMOTE_SCHEMES:
        if loc.startswith(scheme):
            raise NotImplementedError(
                f"remote filesystem scheme {scheme!r} is not implemented on "
                "this build; storage locations (spool, iceberg, compile "
                "cache, prewarm manifests) must be local paths"
            )
    return LocalFileSystem()


def strip_scheme(location: str) -> str:
    return location[len("file://"):] if location.startswith("file://") else location
