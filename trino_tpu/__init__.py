"""trino_tpu — a TPU-native distributed SQL query engine.

A from-scratch re-design of the capabilities of Trino (the distributed MPP SQL
engine; reference snapshot surveyed in SURVEY.md) built idiomatically on
JAX/XLA: plan fragments compile to jitted XLA computations over device-resident
columnar batches, cross-worker exchanges lower to ICI collectives
(`all_to_all` / `all_gather` / `psum`), and the surrounding runtime (sessions,
scheduling, memory accounting, metrics) is a host-side control plane.

Layer map (mirrors SURVEY.md §1):

    client/        -- client API + CLI                (ref: client/trino-cli, trino-client)
    server/        -- coordinator/worker control plane (ref: core/trino-main/.../server)
    sql/           -- tokenizer/parser/analyzer        (ref: core/trino-parser, sql/analyzer)
    planner/       -- logical plan, optimizer, fragmenter (ref: sql/planner)
    expr/          -- expression IR -> JAX compiler    (ref: sql/relational + sql/gen)
    ops/           -- physical operators (jitted)      (ref: operator/**)
    parallel/      -- mesh, shardings, collectives     (ref: exchange + output buffers)
    runtime/       -- driver, tasks, memory, metrics   (ref: execution/**)
    columnar/      -- device Page/Block analog         (ref: spi/Page.java, spi/block)
    types/         -- SQL type system                  (ref: spi/type)
    connectors/    -- tpch/tpcds/memory/... plugins    (ref: plugin/*)
"""

import jax

# SQL semantics require 64-bit integers (BIGINT keys, decimal-as-i64-cents) and
# 64-bit floats (DOUBLE). The hot paths stay integer/f32; f64 appears only in
# final-aggregation arithmetic so the TPU cost is negligible.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
