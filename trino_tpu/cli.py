"""Interactive SQL terminal (reference: client/trino-cli — cli/Trino.java:40,
Console.java).  Runs in-process by default (LocalQueryRunner), or against a
coordinator with --server (the protocol client).

Usage:
  python -m trino_tpu.cli [--catalog tpch] [--schema tiny]
  python -m trino_tpu.cli --server http://host:8080
  python -m trino_tpu.cli --execute "select 1"
"""

from __future__ import annotations

import argparse
import sys
import time


def format_table(names, rows, max_rows: int = 200) -> str:
    cells = [[("NULL" if v is None else str(v)) for v in r] for r in rows[:max_rows]]
    widths = [len(n) for n in names]
    for r in cells:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(n.ljust(w) for n, w in zip(names, widths)), sep]
    for r in cells:
        out.append(" | ".join(c.ljust(w) for c, w in zip(r, widths)))
    if len(rows) > max_rows:
        out.append(f"... ({len(rows)} rows total)")
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


class _LocalBackend:
    def __init__(self, catalog: str, schema: str):
        from trino_tpu.runtime.runner import LocalQueryRunner

        self.runner = LocalQueryRunner(catalog=catalog, schema=schema)

    def execute(self, sql: str):
        res = self.runner.execute(sql)
        return res.column_names, res.rows


class _RemoteBackend:
    def __init__(self, url: str):
        from trino_tpu.client import Client

        self.client = Client(url)

    def execute(self, sql: str):
        return self.client.execute(sql)


def run_statement(backend, sql: str) -> int:
    t0 = time.perf_counter()
    try:
        names, rows = backend.execute(sql)
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(format_table(names, rows))
    print(f"[{time.perf_counter() - t0:.2f}s]")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", help="coordinator URL (default: in-process)")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--execute", "-e", help="run one statement and exit")
    args = ap.parse_args(argv)

    backend = (
        _RemoteBackend(args.server)
        if args.server
        else _LocalBackend(args.catalog, args.schema)
    )
    if args.execute:
        return run_statement(backend, args.execute)

    print("trino-tpu CLI — end with ';', quit/exit to leave")
    buf: list[str] = []
    while True:
        try:
            line = input("tpu:> " if not buf else "  ..> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if not buf and line.strip().lower() in ("quit", "exit"):
            return 0
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buf).rstrip().rstrip(";")
            buf = []
            if sql.strip():
                run_statement(backend, sql)


if __name__ == "__main__":
    raise SystemExit(main())
