"""SQL tokenizer (reference role: the ANTLR lexer of SqlBase.g4)."""

from __future__ import annotations

from dataclasses import dataclass

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "exists", "between", "like",
    "escape", "is", "null", "true", "false", "case", "when", "then", "else",
    "end", "cast", "try_cast", "extract", "interval", "date", "time",
    "timestamp", "distinct", "all", "any", "some", "union", "intersect",
    "except", "join", "inner", "left", "right", "full", "outer", "cross",
    "on", "using", "natural", "with", "recursive", "values", "asc", "desc",
    "nulls", "first", "last", "create", "table", "drop", "insert", "into",
    "delete", "update", "set", "session", "show", "tables", "schemas",
    "catalogs", "columns", "describe", "explain", "analyze", "if",
    "row", "rows", "fetch", "next", "only", "array", "map", "grouping",
    "rollup", "cube", "over", "partition", "range", "groups", "unbounded", "preceding",
    "following", "current", "filter", "within", "ordinality", "unnest",
    "lateral", "tablesample", "bernoulli", "system", "substring", "for",
    "position", "localtime", "localtimestamp", "current_date",
    "current_time", "current_timestamp", "current_user", "exec", "execute", "prepare",
    "deallocate", "commit", "rollback", "start", "transaction", "work", "use",
    "year", "month", "day", "hour", "minute", "second", "quarter", "week",
    "to", "window",
}

_MULTI_OPS = ("<=", ">=", "<>", "!=", "||", "->", "=>")
#: `|` `{` `}` appear only inside MATCH_RECOGNIZE row patterns ('||' concat
#: still wins via the multi-op scan)
_SINGLE_OPS = "+-*/%(),.;=<>[]?:|{}"


@dataclass(frozen=True)
class Token:
    kind: str  # keyword | ident | qident | number | string | op | eof
    value: str
    pos: int

    def is_kw(self, *kws: str) -> bool:
        return self.kind == "keyword" and self.value in kws


class TokenizeError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise TokenizeError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if ch == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise TokenizeError(f"unterminated string at {i}")
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if ch == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise TokenizeError(f"unterminated identifier at {i}")
            out.append(Token("qident", sql[i + 1 : j], i))
            i = j + 1
            continue
        if ch.isdigit() or (ch == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                c = sql[j]
                if c.isdigit():
                    j += 1
                elif c == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif c in "eE" and not seen_exp and j + 1 < n and (
                    sql[j + 1].isdigit()
                    or (sql[j + 1] in "+-" and j + 2 < n and sql[j + 2].isdigit())
                ):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            out.append(Token("number", sql[i:j], i))
            i = j
            continue
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j].lower()
            if word in KEYWORDS:
                out.append(Token("keyword", word, i))
            else:
                out.append(Token("ident", word, i))
            i = j
            continue
        matched = False
        for op in _MULTI_OPS:
            if sql.startswith(op, i):
                out.append(Token("op", op, i))
                i += len(op)
                matched = True
                break
        if matched:
            continue
        if ch in _SINGLE_OPS:
            out.append(Token("op", ch, i))
            i += 1
            continue
        raise TokenizeError(f"unexpected character {ch!r} at {i}")
    out.append(Token("eof", "", n))
    return out
